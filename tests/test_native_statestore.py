"""Native C++ state store: build, delta semantics, zero-copy views, kernel feed."""

import numpy as np
import pytest

from escalator_tpu.native import statestore

pytestmark = pytest.mark.skipif(
    not statestore.available(), reason="native build unavailable"
)


@pytest.fixture
def store():
    return statestore.NativeStateStore(pod_capacity=64, node_capacity=32)


class TestDeltas:
    def test_upsert_and_views(self, store):
        s1 = store.upsert_pod("p1", group=0, cpu_milli=500, mem_bytes=10**9)
        s2 = store.upsert_pod("p2", group=1, cpu_milli=250, mem_bytes=10**8)
        assert s1 != s2
        pv = store.pod_views()
        assert pv["cpu_milli"][s1] == 500
        assert pv["group"][s2] == 1
        assert pv["valid"][s1] == 1
        assert store.pod_count == 2

    def test_upsert_same_uid_updates_in_place(self, store):
        s1 = store.upsert_pod("p1", 0, 500, 10**9)
        s2 = store.upsert_pod("p1", 0, 999, 10**9)
        assert s1 == s2
        assert store.pod_views()["cpu_milli"][s1] == 999
        assert store.pod_count == 1

    def test_delete_and_slot_reuse(self, store):
        s1 = store.upsert_pod("p1", 0, 500, 10**9)
        assert store.delete_pod("p1") == s1
        assert store.pod_views()["valid"][s1] == 0
        assert store.pod_count == 0
        s2 = store.upsert_pod("p2", 0, 100, 10**8)
        assert s2 == s1  # freelist reuse

    def test_delete_missing_returns_minus_one(self, store):
        assert store.delete_pod("ghost") == -1
        assert store.delete_node("ghost") == -1

    def test_node_fields(self, store):
        s = store.upsert_node(
            "n1", group=2, cpu_milli=4000, mem_bytes=16 * 10**9,
            creation_ns=123, tainted=True, cordoned=False, no_delete=True,
            taint_time_sec=1_700_000_000,
        )
        nv = store.node_views()
        assert nv["creation_ns"][s] == 123
        assert nv["tainted"][s] == 1
        assert nv["no_delete"][s] == 1
        assert nv["taint_time_sec"][s] == 1_700_000_000
        assert store.node_slot("n1") == s
        assert store.node_slot("nope") == -1

    def test_views_are_zero_copy(self, store):
        s = store.upsert_pod("p1", 0, 500, 10**9)
        view = store.pod_views()["cpu_milli"]
        store.upsert_pod("p1", 0, 777, 10**9)
        assert view[s] == 777  # same memory, no snapshot

    def test_growth(self):
        store = statestore.NativeStateStore(pod_capacity=2, node_capacity=2)
        for i in range(10):
            store.upsert_pod(f"p{i}", 0, i, i)
        assert store.pod_count == 10
        assert store.pod_capacity >= 10
        pv = store.pod_views()
        slots = [store.pod_slot(f"p{i}") for i in range(10)]
        assert sorted(pv["cpu_milli"][slots]) == list(range(10))

    def test_batch_grow_resume_packed(self):
        """A batch larger than capacity must grow mid-batch and resume at the
        right key — locks the packed NUL-delimited buffer's resume framing
        (an off-by-one in the skip re-join would bind values to wrong keys).
        Varied-length keys make a framing slip detectable."""
        store = statestore.NativeStateStore(pod_capacity=4, node_capacity=4)
        uids = [f"pod-{'x' * (i % 7)}-{i}" for i in range(20)]
        store.upsert_pods_batch(
            uids, np.zeros(20, np.int32),
            np.arange(20, dtype=np.int64), np.full(20, 5, np.int64))
        names = [f"node-{'y' * (i % 5)}-{i}" for i in range(20)]
        store.upsert_nodes_batch(
            names, np.zeros(20, np.int32),
            np.arange(100, 120, dtype=np.int64), np.full(20, 7, np.int64))
        assert store.pod_count == 20 and store.node_count == 20
        pv, nv = store.pod_views(), store.node_views()
        for i, (u, nm) in enumerate(zip(uids, names, strict=True)):
            assert pv["cpu_milli"][store.pod_slot(u)] == i
            assert nv["cpu_milli"][store.node_slot(nm)] == 100 + i

    def test_packed_batch_rejects_nul_in_key(self):
        """An embedded NUL would desynchronize the packed buffer framing —
        must be a clean ValueError, not heap corruption."""
        store = statestore.NativeStateStore(pod_capacity=4, node_capacity=4)
        with pytest.raises(ValueError, match="NUL"):
            store.upsert_pods_batch(
                ["ok", "bad\0key"], np.zeros(2, np.int32),
                np.ones(2, np.int64), np.ones(2, np.int64))
        with pytest.raises(ValueError, match="NUL"):
            store.upsert_nodes_batch(
                ["n\0", "n2"], np.zeros(2, np.int32),
                np.ones(2, np.int64), np.ones(2, np.int64))


class TestKernelFeed:
    def test_decide_from_native_store(self):
        """End-to-end: deltas into the store, zero-copy views into the kernel."""
        from escalator_tpu.core.arrays import ClusterArrays, GroupArrays
        from escalator_tpu.ops import kernel

        store = statestore.NativeStateStore(pod_capacity=64, node_capacity=32)
        for i in range(10):
            store.upsert_pod(f"p{i}", 0, 500, 10**9)
        for i in range(2):
            store.upsert_node(f"n{i}", 0, 1000, 4 * 10**9)

        pods, nodes = store.as_pod_node_arrays()
        G = 1
        groups = GroupArrays(
            min_nodes=np.zeros(G, np.int32),
            max_nodes=np.full(G, 100, np.int32),
            taint_lower=np.full(G, 30, np.int32),
            taint_upper=np.full(G, 45, np.int32),
            scale_up_thr=np.full(G, 70, np.int32),
            slow_rate=np.ones(G, np.int32),
            fast_rate=np.full(G, 2, np.int32),
            locked=np.zeros(G, bool),
            requested_nodes=np.zeros(G, np.int32),
            cached_cpu_milli=np.zeros(G, np.int64),
            cached_mem_bytes=np.zeros(G, np.int64),
            soft_grace_sec=np.full(G, 300, np.int64),
            hard_grace_sec=np.full(G, 900, np.int64),
            emptiest=np.zeros(G, bool),
            valid=np.ones(G, bool),
        )
        cluster = ClusterArrays(groups=groups, pods=pods, nodes=nodes)
        out = kernel.decide_jit(cluster, np.int64(0))
        # 5000m/2000m = 250% -> ceil(2*(250-70)/70) = 6
        assert int(out.nodes_delta[0]) == 6

        # incremental delta: half the pods finish; decision flips to scale-down
        for i in range(9):
            store.delete_pod(f"p{i}")
        out = kernel.decide_jit(cluster, np.int64(0))
        # 500/2000 = 25% < 30 -> -fast
        assert int(out.nodes_delta[0]) == -2


class TestViewSafety:
    def test_views_stable_across_growth(self):
        """Growth within the lifetime max never reallocates: old views still read
        the same memory (they just don't see new lanes); generation bumps."""
        store = statestore.NativeStateStore(
            pod_capacity=2, node_capacity=2, max_pods=64, max_nodes=64)
        s0 = store.upsert_pod("p0", 0, 111, 1)
        old_view = store.pod_views()["cpu_milli"]
        gen0 = store.generation
        for i in range(1, 20):  # forces growth past capacity 2
            store.upsert_pod(f"p{i}", 0, i, 1)
        assert store.generation > gen0
        assert old_view[s0] == 111  # old view still valid memory
        assert len(store.pod_views()["cpu_milli"]) == store.pod_capacity

    def test_growth_beyond_max_raises(self):
        store = statestore.NativeStateStore(
            pod_capacity=2, node_capacity=2, max_pods=4, max_nodes=4)
        for i in range(4):
            store.upsert_pod(f"p{i}", 0, i, 1)
        import pytest as _pytest
        with _pytest.raises(MemoryError):
            store.upsert_pod("p-over", 0, 1, 1)

    def test_views_keep_store_alive(self):
        import gc
        store = statestore.NativeStateStore(pod_capacity=8, node_capacity=8)
        s = store.upsert_pod("p1", 0, 424242, 1)
        view = store.pod_views()["cpu_milli"]
        del store
        gc.collect()
        assert view[s] == 424242  # store freed only when views die


class TestDirtyTracking:
    def test_marks_and_drains(self, store):
        s1 = store.upsert_pod("p1", 0, 500, 10**9)
        s2 = store.upsert_pod("p2", 1, 250, 10**8)
        n1 = store.upsert_node("n1", 0, 4000, 16 * 10**9)
        assert store.pod_dirty_count == 2
        assert store.node_dirty_count == 1
        ps, ns = store.drain_dirty()
        assert sorted(ps.tolist()) == sorted([s1, s2])
        assert ns.tolist() == [n1]
        # drained: reset for the next tick
        assert store.pod_dirty_count == 0
        ps2, ns2 = store.drain_dirty()
        assert len(ps2) == 0 and len(ns2) == 0

    def test_dedupes_repeat_touches(self, store):
        s1 = store.upsert_pod("p1", 0, 500, 10**9)
        store.upsert_pod("p1", 0, 600, 10**9)
        store.upsert_pod("p1", 0, 700, 10**9)
        assert store.pod_dirty_count == 1
        ps, _ = store.drain_dirty()
        assert ps.tolist() == [s1]

    def test_delete_marks_dirty(self, store):
        s1 = store.upsert_pod("p1", 0, 500, 10**9)
        n1 = store.upsert_node("n1", 0, 4000, 16 * 10**9)
        store.drain_dirty()
        store.delete_pod("p1")
        store.delete_node("n1")
        ps, ns = store.drain_dirty()
        assert ps.tolist() == [s1]
        assert ns.tolist() == [n1]

    def test_remark_after_drain(self, store):
        s1 = store.upsert_pod("p1", 0, 500, 10**9)
        store.drain_dirty()
        store.upsert_pod("p1", 0, 999, 10**9)
        ps, _ = store.drain_dirty()
        assert ps.tolist() == [s1]


class TestBatchIngest:
    def test_pods_batch_matches_single(self, store):
        store.upsert_pods_batch(
            ["a", "b", "c"], [0, 1, 2], [100, 200, 300],
            [10**8, 2 * 10**8, 3 * 10**8], [5, -1, 7],
        )
        assert store.pod_count == 3
        pv = store.pod_views()
        sa = store.pod_slot("a")
        assert pv["cpu_milli"][sa] == 100
        assert pv["node"][store.pod_slot("c")] == 7
        assert store.pod_dirty_count == 3

    def test_nodes_batch(self, store):
        store.upsert_nodes_batch(
            ["n1", "n2"], [0, 1], [4000, 8000], [16 * 10**9, 32 * 10**9],
            creation_ns=[10, 20], tainted=[0, 1], taint_time_sec=[0, 12345],
        )
        nv = store.node_views()
        s2 = store.node_slot("n2")
        assert nv["tainted"][s2] == 1
        assert nv["taint_time_sec"][s2] == 12345
        assert store.node_dirty_count == 2

    def test_batch_grows_on_capacity(self):
        s = statestore.NativeStateStore(pod_capacity=4, node_capacity=4)
        s.upsert_pods_batch(
            [f"p{i}" for i in range(20)], np.zeros(20), np.full(20, 100),
            np.full(20, 10**8),
        )
        assert s.pod_count == 20
        assert s.pod_capacity >= 20
        ps, _ = s.drain_dirty()
        assert len(ps) == 20


class TestModelFuzz:
    """Randomized op sequences vs a Python dict model: live-set contents,
    slot stability, and dirty-set semantics must match exactly."""

    def test_random_ops_match_model(self):
        rng = np.random.default_rng(42)
        store = statestore.NativeStateStore(pod_capacity=64, node_capacity=64)
        model = {}            # uid -> (group, cpu, mem)
        dirty_expected = set()  # slots touched since last drain

        for step in range(3000):
            op = rng.integers(0, 10)
            uid = f"p{rng.integers(0, 80)}"
            if op < 6:  # upsert (mix of insert + update)
                vals = (int(rng.integers(0, 8)), int(rng.integers(1, 10**6)),
                        int(rng.integers(1, 10**12)))
                store.upsert_pod(uid, *vals)
                model[uid] = vals
                dirty_expected.add(store.pod_slot(uid))
            elif op < 8:  # delete
                slot = store.delete_pod(uid)
                if uid in model:
                    assert slot >= 0
                    del model[uid]
                    dirty_expected.add(slot)
                else:
                    assert slot == -1
            else:  # drain and cross-check dirty set
                pod_dirty, _ = store.drain_dirty()
                assert set(int(s) for s in pod_dirty) == dirty_expected
                dirty_expected.clear()

            if step % 500 == 0:
                pods, _ = store.as_pod_node_arrays()
                live = {
                    u: (int(pods.group[s]), int(pods.cpu_milli[s]),
                        int(pods.mem_bytes[s]))
                    for u in model
                    for s in [store.pod_slot(u)]
                }
                assert live == model
                assert int(pods.valid.sum()) == len(model)

        # final full cross-check
        pods, _ = store.as_pod_node_arrays()
        assert int(pods.valid.sum()) == len(model)
        for u, vals in model.items():
            s = store.pod_slot(u)
            assert (int(pods.group[s]), int(pods.cpu_milli[s]),
                    int(pods.mem_bytes[s])) == vals
