"""Device resource observatory (round 15): buffer-accounting registry,
executable budgets, the memory growth watchdog, compile attribution, and
profiler capture — plus the inertness contract (the layer is hook-side
only; traced programs are byte-identical with it armed or disabled)."""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

from escalator_tpu import observability as obs
from escalator_tpu.observability import jaxmon, resources as res, spans


@pytest.fixture(autouse=True)
def _watchdog_hygiene():
    res.MEMORY_WATCHDOG.reset()
    yield
    res.MEMORY_WATCHDOG.reset()
    res.PROFILER.abort()


# ------------------------------------------------------------ accounting
def test_registry_counts_metadata_bytes_and_prunes_dead_refs():
    class Owner:
        def __init__(self, arrays):
            self.arrays = arrays

    o = Owner([np.zeros(100, np.int64), np.zeros(7, np.int32)])
    reg = res.RESOURCES.register("test_owner", o, lambda x: x.arrays)
    try:
        snap = res.RESOURCES.snapshot()["test_owner"]
        assert snap["nbytes"] == 800 + 28
        assert snap["arrays"] == 2 and snap["instances"] == 1
        # dead referent: the entry prunes itself on the next snapshot
        del o
        import gc

        gc.collect()
        assert "test_owner" not in res.RESOURCES.snapshot()
    finally:
        reg.close()


def test_registry_walks_dataclasses_tuples_and_none():
    from escalator_tpu.fleet.service import _empty_pods

    class Owner:
        def __init__(self):
            self.state = (_empty_pods(4), None, [np.zeros(3, np.int8)])

    o = Owner()
    reg = res.RESOURCES.register("test_tree", o, lambda x: x.state)
    try:
        snap = res.RESOURCES.snapshot()["test_tree"]
        # PodArrays(4): group i32 + cpu i64 + mem i64 + node i32 + valid b
        assert snap["nbytes"] == 4 * (4 + 8 + 8 + 4 + 1) + 3
        assert snap["arrays"] == 6
    finally:
        reg.close()


def test_provider_error_degrades_to_error_field():
    class Owner:
        pass

    o = Owner()

    def bad(_x):
        raise RuntimeError("provider exploded")

    reg = res.RESOURCES.register("test_bad", o, bad)
    try:
        snap = res.RESOURCES.snapshot()["test_bad"]
        assert "provider exploded" in snap["error"]
        assert snap["nbytes"] == 0
    finally:
        reg.close()


# ------------------------------------------- decider owners + budgets
@pytest.fixture(scope="module")
def decider_world():
    import jax  # noqa: F401

    from escalator_tpu.analysis.registry import representative_cluster
    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import make_state_store
    from escalator_tpu.ops.device_state import (
        DeviceClusterCache,
        IncrementalDecider,
    )

    G = 4
    store = make_state_store(pod_capacity=1 << 7, node_capacity=1 << 5)
    store.upsert_pods_batch([f"rp{i}" for i in range(40)],
                            np.arange(40) % G,
                            np.full(40, 500), np.full(40, 10**9))
    store.upsert_nodes_batch([f"rn{i}" for i in range(12)],
                             np.arange(12) % G,
                             np.full(12, 4000), np.full(12, 16 * 10**9))
    pods_v, nodes_v = store.as_pod_node_arrays()
    groups = representative_cluster(G, 1, 1, seed=42).groups
    store.drain_dirty()
    cache = DeviceClusterCache(
        ClusterArrays(groups=groups, pods=pods_v, nodes=nodes_v))
    inc = IncrementalDecider(cache, refresh_every=0)
    inc.decide(np.int64(1_700_000_000), False)
    return store, cache, inc, G


def test_decider_owner_budgets_match_measured(decider_world):
    _store, cache, inc, G = decider_world
    snap = res.RESOURCES.snapshot()
    for owner in ("cluster_arrays", "group_aggregates", "decision_columns"):
        rows = snap[owner]
        assert rows["nbytes"] > 0
        assert rows["nbytes"] == rows["budget_bytes"], (owner, rows)
    # formula vs capacities directly (one instance per owner here —
    # module-scoped fixture, no other decider alive in this module)
    assert snap["cluster_arrays"]["nbytes"] >= res.expected_cluster_bytes(
        cache.pod_capacity, cache.node_capacity, G)
    assert snap["group_aggregates"]["nbytes"] % (
        res.expected_aggregates_bytes(G, cache.node_capacity + 1)) == 0
    assert snap["decision_columns"]["nbytes"] % (
        res.expected_decision_columns_bytes(G)) == 0


def test_budget_formulas_match_real_dtypes():
    """The envelope formulas derive from the REAL constructors, so the
    docs' hand constants (25 B/pod, 40 B/node, 76 B of decision columns)
    are locked against dataclass drift here."""
    from escalator_tpu.fleet.service import _empty_nodes, _empty_pods

    pod_b = sum(getattr(_empty_pods(1), f).dtype.itemsize
                for f in _empty_pods(1).__dataclass_fields__)
    node_b = sum(getattr(_empty_nodes(1), f).dtype.itemsize
                 for f in _empty_nodes(1).__dataclass_fields__)
    assert pod_b == 25 and node_b == 40
    assert res.expected_decision_columns_bytes(1) == 76
    assert res.expected_order_state_bytes(10) == 280
    # fleet arena = (C+1) x (cluster + aggs + columns) at the buckets
    one = (res.expected_cluster_bytes(8, 4, 2)
           + res.expected_aggregates_bytes(2, 5)
           + res.expected_decision_columns_bytes(2))
    assert res.expected_fleet_arena_bytes(3, 2, 8, 4) == 4 * one


# ------------------------------------------------------------ capability
def test_capabilities_degrade_to_unsupported_not_raise():
    caps = res.capabilities()
    assert set(caps) == {"memory_stats", "live_arrays", "profiler"}
    # CPU rig (tests/conftest pins cpu): memory_stats reports nothing —
    # the surfaces must say so explicitly instead of raising
    mem = res.device_memory()
    assert isinstance(mem, dict) and mem
    for stats in mem.values():
        assert ("unsupported" in stats) or ("bytes_in_use" in stats)
    la = res.live_arrays_bytes()
    assert ("unsupported" in la) or (la["nbytes"] >= 0)
    section = res.memory_section()
    assert {"owners", "total_registered_bytes", "device", "live_arrays",
            "capabilities", "watchdog"} <= set(section)


# ------------------------------------------------------------- watchdog
def test_forced_leak_fires_memory_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_WATCH", "6")
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_MIN_GROWTH", "100")
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_DUMP_INTERVAL_SEC", "3600")
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_SAMPLE_EVERY", "1")

    class Leaky:
        def __init__(self):
            self.arrays = []

    leaky = Leaky()
    reg = res.RESOURCES.register("test_leak", leaky, lambda o: o.arrays)
    res.MEMORY_WATCHDOG.reset()
    try:
        fired = []
        for _ in range(8):
            leaky.arrays.append(np.zeros(64, np.int64))
            with spans.span("leak_tick"):
                pass
            fired.append(res.MEMORY_WATCHDOG.dumps)
        res.MEMORY_WATCHDOG.drain()
        dumps = sorted(tmp_path.glob("escalator-tpu-flight-memory-*.json"))
        assert len(dumps) == 1, dumps   # rate limit holds after the first
        doc = json.loads(dumps[0].read_text())
        assert doc["reason"] == "memory"
        wd = doc["memory_watchdog"]
        assert wd["growth_bytes"] > 0 and wd["window_ticks"] == 6
        assert wd["owners"]["test_leak"] > 0
        # the dump's memory section names the leaking owner too
        assert doc["memory"]["owners"]["test_leak"]["nbytes"] > 0
        assert res.MEMORY_WATCHDOG.breaches >= 1
    finally:
        reg.close()


def test_flat_buffers_never_breach(tmp_path, monkeypatch):
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_WATCH", "4")
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_MIN_GROWTH", "1")
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_SAMPLE_EVERY", "1")

    class Flat:
        def __init__(self):
            self.arrays = [np.zeros(64)]

    flat = Flat()
    reg = res.RESOURCES.register("test_flat", flat, lambda o: o.arrays)
    res.MEMORY_WATCHDOG.reset()
    try:
        for _ in range(12):
            with spans.span("flat_tick"):
                pass
        assert res.MEMORY_WATCHDOG.breaches == 0
        assert not list(tmp_path.glob("escalator-tpu-flight-memory-*"))
    finally:
        reg.close()


def test_watchdog_off_switch(monkeypatch):
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_WATCH", "off")
    res.MEMORY_WATCHDOG.reset()
    for _ in range(4):
        with spans.span("off_tick"):
            pass
    assert res.MEMORY_WATCHDOG.state()["samples"] == 0


# ------------------------------------------------------ compile ring
def test_compile_ring_attributes_by_span_path():
    import jax
    import jax.numpy as jnp

    assert jaxmon.install()
    marker = float(np.random.default_rng(123).integers(1, 1 << 30))
    fn = jax.jit(lambda x: x * marker - 0.5)   # never-seen closure
    with spans.span("ring_tick"):
        spans.annotate(backend="ring-test")
        with spans.span("delta_decide", kind="device"):
            spans.fence(fn(jnp.ones(11)))
    ring = jaxmon.compile_ring()
    mine = [r for r in ring if r.get("root") == "ring_tick"]
    assert mine, ring[-3:]
    rec = mine[-1]
    assert rec["entry"] == "kernel.delta_decide"
    assert rec["path"].endswith("delta_decide")
    assert rec["backend"] == "ring-test"
    assert rec["duration_sec"] > 0
    # attribution summary groups + flags against the retrace pins
    rows = jaxmon.attribute_compiles(mine, pins={"kernel.delta_decide": 0})
    row = next(r for r in rows if r["entry"] == "kernel.delta_decide")
    assert row["bust"] is True and row["retrace_budget"] == 0


def test_debug_compiles_cli_reads_dump(tmp_path):
    from escalator_tpu.cli import main as cli_main

    dump_path = tmp_path / "ring.json"
    dump_path.write_text(json.dumps(obs.RECORDER.as_dump("test")))
    assert cli_main(["debug-compiles", "--dump", str(dump_path)]) == 0
    assert cli_main(["debug-compiles", "--dump",
                     str(tmp_path / "missing.json")]) == 2


# ------------------------------------------------------ profiler capture
@pytest.mark.slow   # ~35 s in a full-suite run (the first start_trace in
                    # a process pays ~16 s of profiler init, wait_idle pays
                    # the serialization) — round-17 tier-1 time-neutrality
                    # offset for the journey smoke leg + tests; the REAL
                    # capture path stays tier-1-covered by the smoke's
                    # debug-profile RPC leg, and CI's unfiltered job runs
                    # this in full
def test_profiler_capture_counts_roots_and_writes_trace(tmp_path):
    import jax  # noqa: F401 - capability needs jax loaded

    out_dir = tmp_path / "trace"
    r = res.PROFILER.start(2, str(out_dir))
    assert r["ok"], r
    # a second arm while active reports busy, never a nested trace
    assert res.PROFILER.start(1, str(tmp_path / "other")) == {
        "ok": False, "busy": True}
    with spans.span("prof_tick"):
        pass
    assert res.PROFILER.active
    with spans.span("prof_tick"):
        pass
    # the Kth tick TRIGGERS the stop; serialization runs on a worker (the
    # tick thread must never pay the multi-second stop_trace write)
    assert not res.PROFILER.active
    assert res.PROFILER.wait_idle(120)
    files = res.trace_files(str(out_dir))
    assert any(f.endswith(".xplane.pb") for f in files), files


@pytest.mark.slow   # stop_trace serialization grows with process history:
                    # ~45 s late in a full-suite run — CI's unfiltered test
                    # job covers this path; tier-1 keeps the fast captures
def test_profiler_capture_timeout_ships_partial(tmp_path):
    import jax  # noqa: F401

    holder = {}

    def run():
        holder["r"] = res.PROFILER.capture(
            50, str(tmp_path / "t2"), timeout=0.5)

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.1)
    with spans.span("partial_tick"):
        pass
    # generous join: stop_trace serialization can take several seconds in
    # a long-lived suite process (the profiler carries process metadata)
    t.join(60)
    assert "r" in holder, "capture thread did not finish"
    r = holder["r"]
    assert r["ok"] and r.get("timed_out") is True
    assert res.trace_files(str(tmp_path / "t2"))


@pytest.mark.slow   # ~35 s in a full-suite run (real profiler arm + stop
                    # serialization) — round-17 time-neutrality offset; the
                    # escalation WIRING stays tier-1-covered by the cheap
                    # SLO-escalation test in tests/test_journey.py (same
                    # PROFILER.start contract, stubbed start), CI's
                    # unfiltered job runs the real arm here
def test_tail_profile_escalation_arms_capture(tmp_path, monkeypatch):
    """ESCALATOR_TPU_TAIL_PROFILE=1: the first tail breach that wins the
    dump rate limit also arms a profiler capture of the next K ticks."""
    import jax  # noqa: F401

    from escalator_tpu.observability import histograms, tail

    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_CAPTURE", "2")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_MIN_TICKS", "8")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_PROFILE", "1")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_PROFILE_TICKS", "1")
    histograms.reset()
    tail.WATCHDOG.reset()
    try:
        for _ in range(10):
            histograms.TICKS.observe(("tailprof_tick",), 0.001)
        rec = {"root": "tailprof_tick", "seq": 1, "duration_ms": 500.0}
        assert tail.WATCHDOG.on_record(rec) is True
        assert res.PROFILER.active
        with spans.span("tailprof_tick"):
            pass
        assert not res.PROFILER.active
        assert res.PROFILER.wait_idle(120)
        tail.WATCHDOG.drain()
        dump = next(tmp_path.glob("escalator-tpu-flight-tail-*.json"))
        doc = json.loads(dump.read_text())
        assert doc["tail"]["profile"]["ok"] is True
        prof_dirs = list(tmp_path.glob("escalator-tpu-profile-tail-*"))
        assert prof_dirs and res.trace_files(str(prof_dirs[0]))
    finally:
        histograms.reset()
        tail.WATCHDOG.reset()


# --------------------------------------------------------------- inertness
def test_jaxprs_byte_identical_with_resources_armed(decider_world,
                                                    monkeypatch):
    """The observatory is hook-side only: tracing a registered jaxlint
    entry with the resources layer armed (owners registered, watchdog
    sampling every tick, compile ring recording) yields a jaxpr
    byte-identical to the layer disabled — no budget, donation or callback
    invariant moves."""
    import jax

    from escalator_tpu.analysis.registry import default_registry

    entries = {e.name: e for e in default_registry()}
    for name in ("kernel.delta_decide", "device_state.scatter_update_aggs"):
        traced = entries[name].build()

        def jaxpr_text():
            return str(jax.make_jaxpr(traced.fn)(*traced.args))

        monkeypatch.setenv("ESCALATOR_TPU_MEMORY_WATCH", "off")
        plain = jaxpr_text()
        monkeypatch.setenv("ESCALATOR_TPU_MEMORY_WATCH", "4")
        monkeypatch.setenv("ESCALATOR_TPU_MEMORY_SAMPLE_EVERY", "1")
        with spans.span("armed_trace"):
            armed = jaxpr_text()
        assert armed == plain, f"{name}: jaxpr changed under resources"


# ------------------------------------------------------- dump integration
def test_flight_dump_carries_memory_and_compiles(decider_world):
    doc = obs.RECORDER.as_dump("test")
    assert "memory" in doc
    assert doc["memory"]["total_registered_bytes"] > 0
    assert "cluster_arrays" in doc["memory"]["owners"]
    assert doc.get("compiles"), "compile ring missing from the dump"
