"""Device-resident cluster cache: O(changes) scatter path == full repack.

The invariant that makes the incremental path safe: after any sequence of store
mutations + drain/apply cycles, decisions computed from the resident arrays must be
identical to decisions computed from a fresh full upload of the store's views.
"""

import numpy as np
import pytest

from escalator_tpu.core import semantics as sem
from escalator_tpu.core.arrays import ClusterArrays, pack_groups
from escalator_tpu.native import statestore
from escalator_tpu.ops.device_state import DeviceClusterCache, _bucket
from escalator_tpu.ops.kernel import decide_jit

pytestmark = pytest.mark.skipif(
    not statestore.available(), reason="native build unavailable"
)

CFG = sem.GroupConfig(
    min_nodes=0,
    max_nodes=10**6,
    taint_lower_percent=30,
    taint_upper_percent=45,
    scale_up_percent=70,
    slow_removal_rate=1,
    fast_removal_rate=2,
    soft_delete_grace_sec=300,
    hard_delete_grace_sec=900,
)


def _groups(n):
    return pack_groups(
        [
            (CFG, sem.GroupState(cached_cpu_milli=4000, cached_mem_bytes=16 * 10**9))
            for _ in range(n)
        ]
    )


def _decide_full(store, groups, now):
    import jax

    pods, nodes = store.as_pod_node_arrays()
    cluster = ClusterArrays(groups=groups, pods=pods, nodes=nodes)
    # fresh full upload (copies the views), deliberately NOT the cache path
    return decide_jit(jax.device_put(cluster), now)


def _assert_same_decisions(a, b):
    np.testing.assert_array_equal(np.asarray(a.status), np.asarray(b.status))
    np.testing.assert_array_equal(
        np.asarray(a.nodes_delta), np.asarray(b.nodes_delta)
    )
    np.testing.assert_array_equal(np.asarray(a.num_pods), np.asarray(b.num_pods))
    np.testing.assert_array_equal(
        np.asarray(a.cpu_request_milli), np.asarray(b.cpu_request_milli)
    )
    np.testing.assert_array_equal(np.asarray(a.reap_mask[:-1]), np.asarray(b.reap_mask))


class TestBucket:
    def test_power_of_two_floor_64(self):
        assert _bucket(0) == 64
        assert _bucket(1) == 64
        assert _bucket(64) == 64
        assert _bucket(65) == 128
        assert _bucket(1000) == 1024


class TestIncrementalParity:
    def test_random_churn_matches_full_repack(self):
        rng = np.random.default_rng(42)
        store = statestore.NativeStateStore(pod_capacity=256, node_capacity=128)
        groups = _groups(8)
        now = np.int64(1_700_000_000)

        for i in range(100):
            store.upsert_pod(f"p{i}", int(rng.integers(0, 8)), 500, 10**9)
        for i in range(40):
            store.upsert_node(
                f"n{i}", int(rng.integers(0, 8)), 4000, 16 * 10**9,
                creation_ns=int(rng.integers(1, 10**12)),
            )
        store.drain_dirty()
        pods, nodes = store.as_pod_node_arrays()
        cache = DeviceClusterCache(ClusterArrays(groups=groups, pods=pods, nodes=nodes))

        for _tick in range(5):
            # mixed churn: updates, inserts, deletes, node taints
            for _ in range(30):
                op = rng.integers(0, 4)
                if op == 0:
                    store.upsert_pod(
                        f"p{rng.integers(0, 120)}", int(rng.integers(0, 8)),
                        int(rng.choice([100, 250, 500, 1000])), 10**9,
                    )
                elif op == 1:
                    store.delete_pod(f"p{rng.integers(0, 120)}")
                elif op == 2:
                    store.upsert_node(
                        f"n{rng.integers(0, 50)}", int(rng.integers(0, 8)),
                        4000, 16 * 10**9,
                        creation_ns=int(rng.integers(1, 10**12)),
                        tainted=bool(rng.integers(0, 2)),
                        taint_time_sec=now - int(rng.integers(0, 2000)),
                    )
                else:
                    store.delete_node(f"n{rng.integers(0, 50)}")
            ps, ns = store.drain_dirty()
            cache.apply_dirty(ps, ns, groups)
            incremental = decide_jit(cache.cluster, now)
            full = _decide_full(store, groups, now)
            _assert_same_decisions(incremental, full)

    def test_fused_apply_and_decide_matches_two_step(self):
        """apply_dirty_and_decide == apply_dirty + decide_jit on the same churn."""
        rng = np.random.default_rng(7)
        store = statestore.NativeStateStore(pod_capacity=256, node_capacity=128)
        store2 = statestore.NativeStateStore(pod_capacity=256, node_capacity=128)
        groups = _groups(8)
        now = np.int64(1_700_000_000)
        for s in (store, store2):
            for i in range(100):
                s.upsert_pod(f"p{i}", i % 8, 500, 10**9)
            for i in range(40):
                s.upsert_node(f"n{i}", i % 8, 4000, 16 * 10**9, creation_ns=i + 1)
            s.drain_dirty()
        p1, n1 = store.as_pod_node_arrays()
        p2, n2 = store2.as_pod_node_arrays()
        fused = DeviceClusterCache(ClusterArrays(groups=groups, pods=p1, nodes=n1))
        twostep = DeviceClusterCache(ClusterArrays(groups=groups, pods=p2, nodes=n2))

        for tick in range(3):
            for k in range(20):
                for s in (store, store2):
                    s.upsert_pod(f"p{(tick * 20 + k) % 110}", k % 8, 250, 10**9)
                    if k % 5 == 0:
                        s.delete_node(f"n{(tick + k) % 45}")
            ps, ns = store.drain_dirty()
            out_fused = fused.apply_dirty_and_decide(ps, ns, now, groups)
            ps2, ns2 = store2.drain_dirty()
            twostep.apply_dirty(ps2, ns2, groups)
            out_two = decide_jit(twostep.cluster, now)
            # both sides carry the cache's scratch lane: compare verbatim
            for f in ("status", "nodes_delta", "num_pods", "cpu_request_milli",
                      "reap_mask", "node_pods_remaining", "num_untainted"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(out_fused, f)),
                    np.asarray(getattr(out_two, f)),
                    err_msg=f,
                )

    def test_packed_transfer_matches_per_column(self):
        """apply_dirty_packed (two byte-buffer transfers) must leave the
        resident cluster BIT-identical to apply_dirty (sixteen per-column
        transfers) on the same churn — the bitcast round-trip through the
        packed layout is exact for every column dtype incl. bool."""
        import jax

        rng = np.random.default_rng(13)
        stores = [
            statestore.NativeStateStore(pod_capacity=256, node_capacity=128)
            for _ in range(2)
        ]
        groups = _groups(8)
        now = np.int64(1_700_000_000)
        for s in stores:
            for i in range(100):
                s.upsert_pod(f"p{i}", i % 8, 500, 10**9)
            for i in range(40):
                s.upsert_node(f"n{i}", i % 8, 4000, 16 * 10**9,
                              creation_ns=i + 1)
            s.drain_dirty()
        caches = [
            DeviceClusterCache(ClusterArrays(
                groups=groups, pods=s.as_pod_node_arrays()[0],
                nodes=s.as_pod_node_arrays()[1]))
            for s in stores
        ]
        # regenerate identical churn per store (same seed stream)
        for _tick in range(3):
            ops = [(int(rng.integers(0, 120)), int(rng.integers(0, 8)),
                    int(rng.choice([100, 250, 1000])),
                    int(rng.integers(0, 50)), bool(rng.integers(0, 2)))
                   for _ in range(25)]
            for s in stores:
                for (pi, g, cpu, ni, taint) in ops:
                    s.upsert_pod(f"p{pi}", g, cpu, 10**9)
                    s.upsert_node(f"n{ni}", ni % 8, 4000, 16 * 10**9,
                                  creation_ns=ni + 1, tainted=taint,
                                  taint_time_sec=int(now) - 5)
            ps0, ns0 = stores[0].drain_dirty()
            caches[0].apply_dirty(ps0, ns0, groups)
            ps1, ns1 = stores[1].drain_dirty()
            caches[1].apply_dirty_packed(ps1, ns1, groups)
            a, _ = jax.tree_util.tree_flatten(caches[0].cluster)
            b, _ = jax.tree_util.tree_flatten(caches[1].cluster)
            for x, y in zip(a, b, strict=True):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        _assert_same_decisions(
            decide_jit(caches[1].cluster, now),
            _decide_full(stores[1], groups, now),
        )

    def test_empty_delta_tick(self):
        store = statestore.NativeStateStore(pod_capacity=64, node_capacity=32)
        groups = _groups(2)
        store.upsert_pod("p0", 0, 500, 10**9)
        store.upsert_node("n0", 0, 4000, 16 * 10**9)
        store.drain_dirty()
        pods, nodes = store.as_pod_node_arrays()
        cache = DeviceClusterCache(ClusterArrays(groups=groups, pods=pods, nodes=nodes))
        before = decide_jit(cache.cluster, np.int64(0))
        ps, ns = store.drain_dirty()
        cache.apply_dirty(ps, ns, groups)
        after = decide_jit(cache.cluster, np.int64(0))
        np.testing.assert_array_equal(
            np.asarray(before.nodes_delta), np.asarray(after.nodes_delta)
        )

    def test_group_state_rides_along(self):
        """Lock flips (host GroupState) must reach the device without node churn."""
        store = statestore.NativeStateStore(pod_capacity=64, node_capacity=32)
        store.upsert_pod("p0", 0, 3900, 10**9)
        store.upsert_node("n0", 0, 4000, 16 * 10**9)
        store.drain_dirty()
        pods, nodes = store.as_pod_node_arrays()
        groups = _groups(1)
        cache = DeviceClusterCache(ClusterArrays(groups=groups, pods=pods, nodes=nodes))
        out = decide_jit(cache.cluster, np.int64(0))
        assert int(out.status[0]) == sem.DecisionStatus.OK

        locked = _groups(1)
        locked.locked[0] = True
        locked.requested_nodes[0] = 5
        cache.apply_dirty(np.empty(0, np.int64), np.empty(0, np.int64), locked)
        out2 = decide_jit(cache.cluster, np.int64(0))
        assert int(out2.status[0]) == sem.DecisionStatus.LOCKED
        assert int(out2.nodes_delta[0]) == 5

    def test_set_host_shape_mismatch_raises(self):
        store = statestore.NativeStateStore(pod_capacity=64, node_capacity=32)
        store.upsert_pod("p0", 0, 500, 10**9)
        pods, nodes = store.as_pod_node_arrays()
        cache = DeviceClusterCache(
            ClusterArrays(groups=_groups(1), pods=pods, nodes=nodes)
        )
        store.grow(128, 32)
        pods2, nodes2 = store.as_pod_node_arrays()
        with pytest.raises(ValueError):
            cache.set_host(pods2, nodes2)
        # refresh_full is the growth path
        cache.refresh_full(ClusterArrays(groups=_groups(1), pods=pods2, nodes=nodes2))
        assert cache.pod_capacity == 128
