"""Threaded soak test — the ``go test -race`` analog (SURVEY.md §5: the reference
runs its whole suite under the race detector, Makefile:13-14). Python has no tsan,
so this drives the actual racy interleaving instead: the controller ticks on one
thread while watch events mutate the cluster from others, across the backends
that share state with the ingest path (golden via the RLock'd in-memory client,
native via the C++ store's single-writer lock) plus the grid-mesh backend,
whose lister-walk repack must stay torn-snapshot-free under the same churn
and whose sharded decide must still match the fresh golden oracle after the
mutators quiesce. Correctness oracle: after the
mutators quiesce, one more decision through the soaked backend must match a fresh
golden evaluation of the same final state — a torn snapshot or a lost dirty mark
would leave the device-resident arrays permanently diverged, which is exactly what
this catches."""

import threading

import numpy as np
import pytest

from escalator_tpu.controller import controller as ctl
from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.controller.backend import GoldenBackend, GridJaxBackend
from escalator_tpu.controller.native_backend import make_native_backend
from escalator_tpu.k8s.cache import EventfulClient
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)
from escalator_tpu.testsupport.cloud_provider import (
    MockBuilder,
    MockCloudProvider,
    MockNodeGroup,
)
from escalator_tpu.utils.clock import MockClock

LABEL_KEY = "customer"
LABEL_VALUE = "soak"

# ESCALATOR_TPU_SOAK_SCALE multiplies the soak's event/tick volume for
# on-demand long runs (CI keeps the 1x defaults; threads are never scaled)
from escalator_tpu.testsupport import soak_scale as _soak_scale

_SCALE = _soak_scale()
TICKS = 12 * _SCALE
EVENTS_PER_THREAD = 150 * _SCALE
MUTATOR_THREADS = 2


def _opts():
    return ngmod.NodeGroupOptions(
        name="soak",
        label_key=LABEL_KEY,
        label_value=LABEL_VALUE,
        cloud_provider_group_name="soak-asg",
        min_nodes=1,
        max_nodes=300,
        taint_upper_capacity_threshold_percent=45,
        taint_lower_capacity_threshold_percent=30,
        scale_up_threshold_percent=70,
        slow_node_removal_rate=1,
        fast_node_removal_rate=2,
        soft_delete_grace_period="5m",
        hard_delete_grace_period="15m",
        scale_up_cool_down_period="10m",
    )


def _build_world(backend_kind: str):
    opts = _opts()
    nodes = build_test_nodes(
        12,
        NodeOpts(cpu=4000, mem=16 << 30, label_key=LABEL_KEY,
                 label_value=LABEL_VALUE),
    )
    pods = build_test_pods(
        60,
        PodOpts(cpu=[200], mem=[512 << 20], node_selector_key=LABEL_KEY,
                node_selector_value=LABEL_VALUE),
    )
    client = EventfulClient(nodes=nodes, pods=pods)
    if backend_kind == "native":
        backend = make_native_backend(client, [opts])
    elif backend_kind == "grid":
        backend = GridJaxBackend()
    else:
        backend = GoldenBackend()
    provider = MockCloudProvider()
    provider.register_node_group(
        MockNodeGroup("soak-asg", "soak", min_size=1, max_size=300,
                      target_size=len(nodes))
    )
    controller = ctl.Controller(
        ctl.Opts(
            client=client,
            node_groups=[opts],
            cloud_provider_builder=MockBuilder(provider),
            dry_mode=False,
            backend=backend,
            clock=MockClock(),
        )
    )
    return client, controller


def _mutator(client: EventfulClient, seed: int, stop: threading.Event,
             errors: list):
    """Churn pods and nodes through the watch path: adds, deletes, phase flips
    (which the informer semantics turn into watch deletes), node adds."""
    rng = np.random.default_rng(seed)
    try:
        for _ in range(EVENTS_PER_THREAD):
            if stop.is_set():
                return
            roll = int(rng.integers(0, 10))
            if roll < 4:
                client.add_pod(
                    build_test_pods(1, PodOpts(
                        cpu=[int(rng.integers(50, 400))],
                        mem=[int(rng.integers(1, 4)) << 28],
                        node_selector_key=LABEL_KEY,
                        node_selector_value=LABEL_VALUE))[0]
                )
            elif roll < 6:
                pods = client.list_pods()
                if pods:
                    client.remove_pod(pods[int(rng.integers(0, len(pods)))])
            elif roll < 8:
                pods = client.list_pods()
                if pods:
                    p = pods[int(rng.integers(0, len(pods)))]
                    p.phase = "Succeeded" if roll == 6 else "Running"
                    client.update_pod(p)
            else:
                client.add_node(
                    build_test_nodes(1, NodeOpts(
                        cpu=4000, mem=16 << 30, label_key=LABEL_KEY,
                        label_value=LABEL_VALUE))[0]
                )
    except Exception as e:  # pragma: no cover - the failure this test hunts
        errors.append(e)


@pytest.mark.parametrize("backend_kind", ["golden", "native", "grid"])
def test_soak_ticks_while_watch_mutates(backend_kind):
    client, controller = _build_world(backend_kind)
    stop = threading.Event()
    errors: list = []
    threads = [
        threading.Thread(
            target=_mutator, args=(client, 1000 + t, stop, errors), daemon=True
        )
        for t in range(MUTATOR_THREADS)
    ]
    for t in threads:
        t.start()
    try:
        for _ in range(TICKS):
            controller.run_once()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert not errors, f"mutator thread crashed: {errors[0]!r}"
    assert all(not t.is_alive() for t in threads)

    # Quiesced oracle: the soaked backend must agree with a fresh golden
    # evaluation of the same final cluster state.
    state = controller.node_groups["soak"]
    state.kernel_state.locked = state.scale_lock.locked()
    state.kernel_state.requested_nodes = state.scale_lock.requested_nodes
    now_sec = int(controller.clock.now())
    pods = state.pod_lister.list()
    nodes = state.node_lister.list()
    backend_objects = (pods, nodes) if controller.backend.needs_objects else ([], [])
    soaked = controller.backend.decide(
        [(backend_objects[0], backend_objects[1],
          state.opts.to_group_config(), state.kernel_state)],
        now_sec,
        dry_mode_flags=[False],
        taint_trackers=[state.taint_tracker],
    )[0].decision
    golden = GoldenBackend().decide(
        [(pods, nodes, state.opts.to_group_config(), state.kernel_state)],
        now_sec,
        dry_mode_flags=[False],
        taint_trackers=[state.taint_tracker],
    )[0].decision
    assert soaked.status == golden.status
    assert soaked.nodes_delta == golden.nodes_delta
    assert soaked.num_pods == golden.num_pods
    assert soaked.num_nodes == golden.num_nodes
    assert soaked.cpu_request_milli == golden.cpu_request_milli
    assert soaked.mem_request_bytes == golden.mem_request_bytes
