"""Pod-axis sharding (sequence-parallel analog): bit-exact vs single-device.

Partial segment sums over pod shards psum to exactly the single-device
aggregates (integer addition commutes), so the full DecisionArrays must match
field-for-field on the 8-device virtual CPU mesh the conftest provides.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from escalator_tpu.core.arrays import (  # noqa: E402
    NO_TAINT_TIME, ClusterArrays, GroupArrays, NodeArrays, PodArrays,
)
from escalator_tpu.ops import kernel  # noqa: E402
from escalator_tpu.parallel import podaxis  # noqa: E402
from escalator_tpu.parallel.mesh import make_mesh  # noqa: E402

NOW = np.int64(1_700_000_000)

ALL_FIELDS = (
    "status nodes_delta cpu_percent mem_percent cpu_request_milli "
    "mem_request_bytes cpu_capacity_milli mem_capacity_bytes num_pods "
    "num_nodes num_untainted num_tainted num_cordoned scale_down_order "
    "untainted_offsets untaint_order tainted_offsets reap_mask "
    "node_pods_remaining"
).split()


def _random_cluster(rng, G, P, N, giant_group=False):
    if giant_group:
        # one group owns ~90% of the pods: the case group-sharding cannot split
        pod_group = np.where(
            rng.random(P) < 0.9, 0, rng.integers(0, G, P)
        ).astype(np.int32)
    else:
        pod_group = rng.integers(0, G, P).astype(np.int32)
    tainted = rng.random(N) < 0.25
    return ClusterArrays(
        groups=GroupArrays(
            min_nodes=rng.integers(0, 2, G).astype(np.int32),
            max_nodes=np.full(G, 10**6, np.int32),
            taint_lower=np.full(G, 30, np.int32),
            taint_upper=np.full(G, 45, np.int32),
            scale_up_thr=np.full(G, 70, np.int32),
            slow_rate=np.ones(G, np.int32),
            fast_rate=np.full(G, 3, np.int32),
            locked=rng.random(G) < 0.1,
            requested_nodes=rng.integers(0, 4, G).astype(np.int32),
            cached_cpu_milli=np.full(G, 4000, np.int64),
            cached_mem_bytes=np.full(G, 16 * 10**9, np.int64),
            soft_grace_sec=np.full(G, 300, np.int64),
            hard_grace_sec=np.full(G, 900, np.int64),
            emptiest=np.zeros(G, bool),
            valid=np.ones(G, bool),
        ),
        pods=PodArrays(
            group=pod_group,
            cpu_milli=rng.integers(0, 8000, P).astype(np.int64),
            mem_bytes=rng.integers(0, 32 * 10**9, P).astype(np.int64),
            node=rng.integers(-1, N, P).astype(np.int32),
            valid=rng.random(P) < 0.95,
        ),
        nodes=NodeArrays(
            group=rng.integers(0, G, N).astype(np.int32),
            cpu_milli=np.full(N, 4000, np.int64),
            mem_bytes=np.full(N, 16 * 10**9, np.int64),
            creation_ns=rng.integers(1, 10**12, N).astype(np.int64),
            tainted=tainted,
            cordoned=(~tainted) & (rng.random(N) < 0.05),
            no_delete=rng.random(N) < 0.02,
            taint_time_sec=np.where(
                tainted, int(NOW) - rng.integers(0, 2000, N), NO_TAINT_TIME
            ).astype(np.int64),
            valid=rng.random(N) < 0.97,
        ),
    )


@pytest.mark.parametrize("giant_group", [False, True])
@pytest.mark.parametrize("P", [1000, 1001, 4096])  # 1001: exercises pod padding
def test_podaxis_matches_single_device(P, giant_group):
    rng = np.random.default_rng(P + int(giant_group))
    cluster = _random_cluster(rng, G=16, P=P, N=200, giant_group=giant_group)
    single = kernel.decide_jit(jax.device_put(cluster), NOW)

    mesh = make_mesh()
    assert mesh.devices.size == 8  # conftest's virtual CPU mesh
    padded = podaxis.pad_pods_for_mesh(cluster, mesh)
    placed = podaxis.place(padded, mesh)
    decider = podaxis.make_podaxis_decider(mesh)
    sharded = decider(placed, NOW)

    for f in ALL_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(single, f)), np.asarray(getattr(sharded, f)),
            err_msg=f,
        )


def test_podaxis_on_hybrid_mesh_matches_single_device():
    """The (dcn, ici) two-axis mesh path: multi-axis pod spec + staged psum."""
    from escalator_tpu.parallel.mesh import make_hybrid_mesh

    rng = np.random.default_rng(11)
    cluster = _random_cluster(rng, G=8, P=1003, N=120, giant_group=True)
    single = kernel.decide_jit(jax.device_put(cluster), NOW)
    hybrid = make_hybrid_mesh(num_hosts=2)  # 2 virtual hosts x 4 chips
    placed = podaxis.place(podaxis.pad_pods_for_mesh(cluster, hybrid), hybrid)
    sharded = podaxis.make_podaxis_decider(hybrid)(placed, NOW)
    for f in ALL_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(single, f)), np.asarray(getattr(sharded, f)),
            err_msg=f,
        )


def test_pad_pods_for_mesh_is_noop_when_divisible():
    rng = np.random.default_rng(0)
    cluster = _random_cluster(rng, G=4, P=64, N=16)
    mesh = make_mesh()
    assert podaxis.pad_pods_for_mesh(cluster, mesh) is cluster


def test_podaxis_pallas_impl_matches():
    """impl='pallas' inside the shard region (interpret on CPU) stays exact."""
    rng = np.random.default_rng(5)
    cluster = _random_cluster(rng, G=8, P=2048, N=100)
    # group-contiguous pods so the fast path can engage inside shards
    order = np.argsort(cluster.pods.group, kind="stable")
    for f in cluster.pods.__dataclass_fields__:
        setattr(cluster.pods, f, getattr(cluster.pods, f)[order])
    single = kernel.decide_jit(jax.device_put(cluster), NOW)
    mesh = make_mesh()
    placed = podaxis.place(podaxis.pad_pods_for_mesh(cluster, mesh), mesh)
    sharded = podaxis.make_podaxis_decider(mesh, impl="pallas")(placed, NOW)
    for f in ALL_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(single, f)), np.asarray(getattr(sharded, f)),
            err_msg=f,
        )
