"""2-D grid sharding (groups x pods mesh): bit-exact vs the unsharded kernel.

The grid decider's pod partials psum over the ``pods`` axis into exactly the
single-device aggregates (integer addition commutes), and its decide tail
runs per group block on that block's full node set — so every DecisionArrays
field must match ``vmap(decide)`` on the same stacked cluster bit-for-bit,
for every (Sg, Sp) factorization of the 8-device virtual CPU mesh.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from escalator_tpu.core.arrays import ClusterArrays  # noqa: E402
from escalator_tpu.ops import kernel  # noqa: E402
from escalator_tpu.parallel import grid  # noqa: E402
from tests.test_podaxis import ALL_FIELDS, NOW, _random_cluster  # noqa: E402


def _stacked_cluster(rng, Sg, G, P, N, giant_group=False):
    """[Sg, ...]-stacked cluster: Sg independent shard blocks with identical
    padded shapes, as mesh.pack_cluster_sharded lays them out."""
    shards = [
        _random_cluster(rng, G=G, P=P, N=N, giant_group=giant_group)
        for _ in range(Sg)
    ]
    leaves = [c.tree_flatten()[0] for c in shards]
    stacked = [np.stack(parts) for parts in zip(*leaves, strict=True)]
    return ClusterArrays.tree_unflatten(None, stacked)


def _vmap_baseline(stacked):
    return jax.jit(jax.vmap(lambda c: kernel.decide(c, NOW)))(
        jax.device_put(stacked))


def _assert_all_equal(baseline, sharded):
    for f in ALL_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(baseline, f)), np.asarray(getattr(sharded, f)),
            err_msg=f,
        )


@pytest.mark.parametrize("Sg", [1, 2, 4, 8])  # Sp = 8 // Sg
@pytest.mark.parametrize("P", [1000, 1001])  # 1001: exercises grid pod padding
def test_grid_matches_vmap_decide(Sg, P):
    rng = np.random.default_rng(100 * Sg + P)
    stacked = _stacked_cluster(rng, Sg=Sg, G=8, P=P, N=96)
    baseline = _vmap_baseline(stacked)

    mesh = grid.make_grid_mesh(num_group_shards=Sg)
    assert mesh.shape == {"groups": Sg, "pods": 8 // Sg}
    placed = grid.place_grid(stacked, mesh)
    sharded = grid.make_grid_decider(mesh)(placed, NOW)
    _assert_all_equal(baseline, sharded)


def test_grid_giant_group_blocks():
    """Each shard block dominated by one giant group — the podaxis regime,
    now with the tail sharded over the 4 group rows as well."""
    rng = np.random.default_rng(7)
    stacked = _stacked_cluster(rng, Sg=4, G=4, P=4096, N=128, giant_group=True)
    baseline = _vmap_baseline(stacked)
    mesh = grid.make_grid_mesh(num_group_shards=4)  # (4 groups, 2 pods)
    sharded = grid.make_grid_decider(mesh)(grid.place_grid(stacked, mesh), NOW)
    _assert_all_equal(baseline, sharded)


def test_grid_pallas_impl_matches():
    """impl='pallas' inside the grid shard region (interpret on CPU)."""
    rng = np.random.default_rng(5)
    stacked = _stacked_cluster(rng, Sg=2, G=8, P=2048, N=64)
    # group-contiguous pods per shard so the fast path can engage
    order = np.argsort(np.asarray(stacked.pods.group), axis=1, kind="stable")
    for f in stacked.pods.__dataclass_fields__:
        arr = np.asarray(getattr(stacked.pods, f))
        setattr(stacked.pods, f, np.take_along_axis(arr, order, axis=1))
    baseline = _vmap_baseline(stacked)
    mesh = grid.make_grid_mesh(num_group_shards=2)  # (2 groups, 4 pods)
    sharded = grid.make_grid_decider(mesh, impl="pallas")(
        grid.place_grid(stacked, mesh), NOW)
    _assert_all_equal(baseline, sharded)


def test_pad_stacked_pods_noop_when_divisible():
    rng = np.random.default_rng(0)
    stacked = _stacked_cluster(rng, Sg=2, G=4, P=64, N=16)
    mesh = grid.make_grid_mesh(num_group_shards=2)
    assert grid.pad_stacked_pods_for_grid(stacked, mesh) is stacked


def test_make_grid_mesh_validates_factorization():
    with pytest.raises(ValueError):
        grid.make_grid_mesh(num_group_shards=3)  # does not divide 8


def test_grid_backend_rejects_bad_mesh():
    from escalator_tpu.controller.backend import GridJaxBackend
    from escalator_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="grid mesh must have axes"):
        GridJaxBackend(mesh=make_mesh())  # 1-D groups-only mesh
    with pytest.raises(ValueError, match="conflicts"):
        GridJaxBackend(mesh=grid.make_grid_mesh(num_group_shards=2),
                       num_group_shards=4)
