"""Killed-leader failover soak: a standby warm-starts from the latest
checkpoint and decides BIT-EXACTLY what an uninterrupted leader would have.

The scenario runs the real ``IncrementalJaxBackend`` (the repack backend
that owns warm starts — docs/ha.md) over a deterministic scripted world:

- run **A** (uninterrupted reference): one backend decides every tick
  ``0..T``;
- run **B** (failover): a *leader* backend with checkpointing decides ticks
  ``0..k`` and dies (mid-"tick": the world keeps evolving, nobody decides);
  a *standby* backend pointed at the same snapshot directory picks up at
  tick ``j > k`` — it must warm-start (flight-recorder phases prove no
  rebuild / no full decide) and from tick ``j`` on produce decisions equal
  to run A's.

Equality holds because decisions are pure functions of (cluster state,
now): the standby's diff-vs-snapshot collapses the missed churn into one
delta batch whose integer aggregate deltas sum to exactly the uninterrupted
run's, and decision columns for groups untouched since their last dirty
tick are identical in both runs by the same argument (locked at the
device_state layer by tests/test_snapshot_restore.py; this file locks the
backend wiring: packer-pad seeding, host-diff baseline adoption, corrupt/
stale fallback).
"""

import glob
import os

import numpy as np
import pytest

from escalator_tpu.controller.backend import IncrementalJaxBackend
from escalator_tpu.core import semantics as sem
from escalator_tpu.observability import RECORDER
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)

NOW = 1_700_000_000


def _config(**kw):
    base = dict(
        min_nodes=0, max_nodes=100, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=70,
        slow_removal_rate=1, fast_removal_rate=2,
    )
    base.update(kw)
    return sem.GroupConfig(**base)


def world_at(t: int):
    """Deterministic scripted world (explicit names — the builders' global
    name counter would make two runs of the 'same' world incomparable):
    two groups whose pod load walks through scale-up / steady / scale-down
    regimes as ``t`` advances, plus taint churn so ordered ticks (and the
    order-state restore) are exercised."""
    from escalator_tpu.testsupport.builders import (
        build_test_node,
        build_test_pod,
    )

    rng = np.random.default_rng(1000 + t)
    # group 0: load ramps up then collapses
    n_pods0 = 8 + 3 * t if t < 6 else max(2, 40 - 5 * t)
    pods0 = [build_test_pod(PodOpts(name=f"g0-p{i}", cpu=[400],
                                    mem=[10**9])) for i in range(n_pods0)]
    nodes0 = [build_test_node(NodeOpts(name=f"g0-n{i}", cpu=2000,
                                       mem=8 * 10**9,
                                       creation_time_ns=(i + 1) * 10**9))
              for i in range(6)]
    # a sliding window of tainted nodes: tainted_any flips over the run
    for i, nd in enumerate(nodes0):
        if t >= 4 and i in ((t // 2) % 6, (t // 2 + 1) % 6):
            nd.taints = [sem_taint(NOW + t - 400)]
    # group 1: steady with small churn in requests
    pods1 = [build_test_pod(PodOpts(
        name=f"g1-p{i}", cpu=[300 + 50 * int(rng.integers(0, 3))],
        mem=[10**9])) for i in range(12)]
    nodes1 = [build_test_node(NodeOpts(name=f"g1-n{i}", cpu=4000,
                                       mem=16 * 10**9,
                                       creation_time_ns=(i + 1) * 10**9))
              for i in range(4)]
    return [
        (pods0, nodes0, _config(), sem.GroupState()),
        (pods1, nodes1, _config(min_nodes=1), sem.GroupState()),
    ]


def sem_taint(ts: int):
    from escalator_tpu.k8s import types as k8s

    return k8s.Taint(key=k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY,
                     value=str(int(ts)))


def decisions_of(results):
    """The comparable decision tuple per group (full Decision + ordered
    name lists — the object-level contract the controller acts on)."""
    return [
        (r.decision,
         [n.name for n in r.scale_down_order],
         [n.name for n in r.untaint_order],
         [n.name for n in r.reap_nodes],
         sorted(r.node_pods_remaining.items()))
        for r in results
    ]


def run_ticks(backend, ticks):
    out = {}
    for t in ticks:
        out[t] = decisions_of(backend.decide(world_at(t), NOW + 60 * t))
    return out


@pytest.fixture
def reference():
    """Run A: the uninterrupted leader over ticks 0..11."""
    return run_ticks(IncrementalJaxBackend(refresh_every=0), range(12))


class TestKilledLeaderFailover:
    def test_standby_warm_start_is_bit_exact(self, tmp_path, reference):
        snap_dir = str(tmp_path / "snaps")
        leader = IncrementalJaxBackend(refresh_every=0,
                                       snapshot_dir=snap_dir,
                                       snapshot_every=1)
        run_ticks(leader, range(5))          # checkpoints every tick
        leader._writer.drain()
        assert leader._writer.checkpoints >= 4
        # leader dies; world evolves unobserved through ticks 5..7

        standby = IncrementalJaxBackend(refresh_every=0,
                                        snapshot_dir=snap_dir)
        depth0 = RECORDER.total_recorded
        got = run_ticks(standby, range(8, 12))
        # bit-exact parity with the uninterrupted run from the first
        # standby tick on — the acceptance bar
        for t in range(8, 12):
            assert got[t] == reference[t], f"standby diverged at tick {t}"
        assert standby._inc is not None and standby._inc.restored
        # the restored aggregates survive their own background audit
        assert standby._inc.drain_audit()
        # flight-recorder proof of the O(1) warm start: the first standby
        # tick restored (snapshot_load + restore phases), never rebuilt
        # residency, and never ran the bootstrap full decide
        first = next(r for r in RECORDER.snapshot()
                     if r["seq"] > depth0 and r.get("restored"))
        phases = {p["name"] for p in first["phases"]}
        assert "snapshot_load" in phases and "restore" in phases
        assert "rebuild_residency" not in phases
        assert "decide_full" not in phases
        assert "host_diff" in phases   # diffed against the snapshot baseline

    def test_corrupt_snapshot_falls_back_cold_with_dump(self, tmp_path,
                                                        reference,
                                                        monkeypatch):
        from escalator_tpu.metrics import metrics

        monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
        snap_dir = str(tmp_path / "snaps")
        leader = IncrementalJaxBackend(refresh_every=0,
                                       snapshot_dir=snap_dir,
                                       snapshot_every=1)
        run_ticks(leader, range(5))
        leader._writer.drain()
        # truncate the checkpoint mid-payload
        path = leader._writer.path
        blob = open(path, "rb").read()
        open(path, "wb").write(blob[: len(blob) // 2])

        before = metrics.snapshot_restores.labels("corrupt")._value.get()
        standby = IncrementalJaxBackend(refresh_every=0,
                                        snapshot_dir=snap_dir)
        got = run_ticks(standby, range(8, 12))
        # cold start still converges to the reference decisions
        for t in range(8, 12):
            assert got[t] == reference[t], f"cold standby diverged at {t}"
        assert standby._inc is not None and not standby._inc.restored
        assert metrics.snapshot_restores.labels(
            "corrupt")._value.get() == before + 1
        dumps = glob.glob(
            os.path.join(str(tmp_path), "*snapshot-corrupt*.json"))
        assert dumps, "corrupt snapshot must dump a flight record"

    def test_outgrown_snapshot_is_discarded_as_stale(self, tmp_path):
        from escalator_tpu.metrics import metrics

        snap_dir = str(tmp_path / "snaps")
        leader = IncrementalJaxBackend(refresh_every=0,
                                       snapshot_dir=snap_dir,
                                       snapshot_every=1)
        run_ticks(leader, range(3))
        leader._writer.drain()

        standby = IncrementalJaxBackend(refresh_every=0,
                                        snapshot_dir=snap_dir)
        before = metrics.snapshot_restores.labels("stale")._value.get()
        # a world that outgrew the checkpoint's pod capacity: the restored
        # state cannot fit and MUST be discarded for a cold rebuild
        big = [(build_test_pods(3000, PodOpts(cpu=[100], mem=[10**8])),
                build_test_nodes(8, NodeOpts(cpu=4000, mem=16 * 10**9)),
                _config(), sem.GroupState())]
        results = standby.decide(big, NOW)
        assert results[0].decision.nodes_delta >= 0   # sane cold decide
        assert metrics.snapshot_restores.labels(
            "stale")._value.get() == before + 1
