"""AWS provider tests against the fake SDK — mirror of the reference's
aws_test.go/node_group_test.go coverage (fleet input construction incl. spot/on-demand
and overrides matrix, attach batching, orphan termination, provider-ID codec,
min/max guards)."""

import pytest

from escalator_tpu.cloudprovider import interface as cp
from escalator_tpu.cloudprovider.aws import aws
from escalator_tpu.cloudprovider.errors import NodeNotInNodeGroupError
from escalator_tpu.k8s import types as k8s
from escalator_tpu.testsupport.aws import FakeAutoScaling, FakeEC2, make_asg
from escalator_tpu.utils.clock import MockClock


def make_provider(asg_name="asg-1", aws_cfg=None, **asg_kw):
    autoscaling = FakeAutoScaling(groups={asg_name: make_asg(asg_name, **asg_kw)})
    ec2 = FakeEC2()
    provider = aws.AWSCloudProvider(autoscaling, ec2, clock=MockClock())
    provider.register_node_groups(
        cp.NodeGroupConfig(
            name="ng", group_id=asg_name, aws=aws_cfg or cp.AWSNodeGroupConfig()
        )
    )
    return provider, autoscaling, ec2


def test_provider_id_codec():
    inst = {"AvailabilityZone": "us-east-1a", "InstanceId": "i-abc123"}
    pid = aws.instance_to_provider_id(inst)
    assert pid == "aws:///us-east-1a/i-abc123"
    assert aws.provider_id_to_instance_id(pid) == "i-abc123"


def test_register_and_refresh():
    provider, autoscaling, _ = make_provider(desired=3)
    ng = provider.get_node_group("asg-1")
    assert ng.target_size() == 3
    autoscaling.groups["asg-1"]["DesiredCapacity"] = 7
    provider.refresh()
    assert ng.target_size() == 7


def test_register_missing_asg_fails():
    autoscaling = FakeAutoScaling(groups={})
    provider = aws.AWSCloudProvider(autoscaling, FakeEC2())
    with pytest.raises(RuntimeError, match="not found on AWS"):
        provider.register_node_groups(cp.NodeGroupConfig(name="x", group_id="nope"))


def test_increase_size_set_desired_capacity():
    provider, autoscaling, _ = make_provider(desired=2, max_size=10)
    ng = provider.get_node_group("asg-1")
    ng.increase_size(3)
    assert ("set_desired_capacity", "asg-1", 5) in autoscaling.calls


def test_increase_size_guards():
    provider, _, _ = make_provider(desired=8, max_size=10)
    ng = provider.get_node_group("asg-1")
    with pytest.raises(ValueError):
        ng.increase_size(0)
    with pytest.raises(RuntimeError, match="breach maximum"):
        ng.increase_size(5)


def test_one_shot_fleet_scale_up_attaches_in_batches():
    cfg = cp.AWSNodeGroupConfig(
        launch_template_id="lt-1", launch_template_version="2",
        fleet_instance_ready_timeout_sec=60,
    )
    provider, autoscaling, ec2 = make_provider(
        desired=0, max_size=100, aws_cfg=cfg
    )
    ng = provider.get_node_group("asg-1")
    ng.increase_size(45)
    fleet_calls = [c for c in ec2.calls if c[0] == "create_fleet"]
    assert len(fleet_calls) == 1
    fi = fleet_calls[1 - 1][1]
    assert fi["Type"] == "instant"
    assert fi["TargetCapacitySpecification"]["TotalTargetCapacity"] == 45
    assert fi["OnDemandOptions"]["MinTargetCapacity"] == 45  # all-or-nothing
    # overrides matrix: 2 subnets, no type overrides
    overrides = fi["LaunchTemplateConfigs"][0]["Overrides"]
    assert [o["SubnetId"] for o in overrides] == ["subnet-1", "subnet-2"]
    # attach in batches of 20: 20+20+5
    batches = [c[2] for c in autoscaling.calls if c[0] == "attach_instances"]
    assert [len(b) for b in batches] == [20, 20, 5]
    assert ng.target_size() == 45


def test_fleet_input_spot_and_type_overrides():
    cfg = cp.AWSNodeGroupConfig(
        launch_template_id="lt-1", lifecycle=aws.LIFECYCLE_SPOT,
        instance_type_overrides=("m5.large", "m5.xlarge"),
        resource_tagging=True,
    )
    provider, _, ec2 = make_provider(desired=0, max_size=100, aws_cfg=cfg)
    ng = provider.get_node_group("asg-1")
    fi = aws.create_fleet_input(ng, 5)
    assert "SpotOptions" in fi and "OnDemandOptions" not in fi
    overrides = fi["LaunchTemplateConfigs"][0]["Overrides"]
    # subnet x type matrix: 2 x 2
    assert len(overrides) == 4
    assert {(o["SubnetId"], o["InstanceType"]) for o in overrides} == {
        ("subnet-1", "m5.large"), ("subnet-1", "m5.xlarge"),
        ("subnet-2", "m5.large"), ("subnet-2", "m5.xlarge"),
    }
    assert fi["TagSpecifications"][0]["Tags"][0]["Key"] == aws.TAG_KEY


def test_fleet_not_ready_terminates_orphans():
    cfg = cp.AWSNodeGroupConfig(
        launch_template_id="lt-1", fleet_instance_ready_timeout_sec=3,
    )
    provider, _, ec2 = make_provider(desired=0, max_size=100, aws_cfg=cfg)
    ec2.all_instances_ready = False
    ng = provider.get_node_group("asg-1")
    with pytest.raises(RuntimeError, match="Not all instances could be started"):
        ng.increase_size(5)
    term_calls = [c for c in ec2.calls if c[0] == "terminate_instances"]
    assert len(term_calls) == 1
    assert len(term_calls[0][1]) == 5
    assert ng.terminate_instances_tries == 1


def test_fleet_three_strikes_circuit_breaker():
    cfg = cp.AWSNodeGroupConfig(
        launch_template_id="lt-1", fleet_instance_ready_timeout_sec=1,
    )
    provider, _, ec2 = make_provider(desired=0, max_size=100, aws_cfg=cfg)
    ec2.all_instances_ready = False
    ng = provider.get_node_group("asg-1")
    for _ in range(2):
        with pytest.raises(RuntimeError):
            ng.increase_size(2)
    with pytest.raises(aws.FleetProvisioningFailure):
        ng.increase_size(2)


def test_fleet_errors_with_no_instances():
    cfg = cp.AWSNodeGroupConfig(launch_template_id="lt-1")
    provider, _, ec2 = make_provider(desired=0, max_size=100, aws_cfg=cfg)
    ec2.fleet_errors = [{"ErrorMessage": "InsufficientInstanceCapacity"}]
    ng = provider.get_node_group("asg-1")
    with pytest.raises(RuntimeError, match="InsufficientInstanceCapacity"):
        ng.increase_size(2)


def test_delete_nodes_decrements_capacity():
    provider, autoscaling, _ = make_provider(
        desired=3, min_size=1, instance_ids=("i-1", "i-2", "i-3")
    )
    ng = provider.get_node_group("asg-1")
    node = k8s.Node(name="n1", provider_id="aws:///us-east-1a/i-2")
    ng.delete_nodes(node)
    assert ("terminate_instance_in_auto_scaling_group", "i-2", True) in \
        autoscaling.calls
    assert autoscaling.groups["asg-1"]["DesiredCapacity"] == 2


def test_delete_nodes_wrong_group_raises_typed_error():
    provider, _, _ = make_provider(desired=3, min_size=0,
                                   instance_ids=("i-1", "i-2", "i-3"))
    ng = provider.get_node_group("asg-1")
    stranger = k8s.Node(name="nX", provider_id="aws:///us-east-1a/i-999")
    with pytest.raises(NodeNotInNodeGroupError):
        ng.delete_nodes(stranger)


def test_delete_nodes_min_size_guards():
    provider, _, _ = make_provider(desired=1, min_size=1, instance_ids=("i-1",))
    ng = provider.get_node_group("asg-1")
    node = k8s.Node(name="n1", provider_id="aws:///us-east-1a/i-1")
    with pytest.raises(RuntimeError, match="min sized reached"):
        ng.delete_nodes(node)


def test_get_instance_launch_time():
    provider, _, ec2 = make_provider(instance_ids=("i-1",))
    ec2.instances["i-1"] = {"InstanceId": "i-1", "LaunchTime": 1234.5}
    node = k8s.Node(name="n1", provider_id="aws:///us-east-1a/i-1")
    inst = provider.get_instance(node)
    assert inst.instantiation_time() == 1234.5
    assert inst.id() == "i-1"


def test_asg_tagging():
    autoscaling = FakeAutoScaling(groups={"asg-1": make_asg("asg-1")})
    provider = aws.AWSCloudProvider(autoscaling, FakeEC2())
    provider.register_node_groups(cp.NodeGroupConfig(
        name="ng", group_id="asg-1",
        aws=cp.AWSNodeGroupConfig(resource_tagging=True),
    ))
    assert any(c[0] == "create_or_update_tags" for c in autoscaling.calls)
    # second registration: tag present, not re-added
    n_tag_calls = sum(1 for c in autoscaling.calls if c[0] == "create_or_update_tags")
    provider.refresh()
    assert sum(
        1 for c in autoscaling.calls if c[0] == "create_or_update_tags"
    ) == n_tag_calls


def test_decrease_target_size():
    provider, autoscaling, _ = make_provider(desired=5, min_size=1)
    ng = provider.get_node_group("asg-1")
    with pytest.raises(ValueError):
        ng.decrease_target_size(1)
    with pytest.raises(RuntimeError, match="breach minimum"):
        ng.decrease_target_size(-5)
    ng.decrease_target_size(-2)
    assert ("set_desired_capacity", "asg-1", 3) in autoscaling.calls
