"""Tick flight recorder + span timelines (escalator_tpu.observability).

Locks the observability-layer contracts:

- spans: nesting/paths, device-fence marking, thread-locality, the disabled
  no-op mode, and remote-phase grafting;
- flight recorder: every backend's tick produces a record with >= 4 named
  device-fenced phases; the ring is bounded; dumps are valid JSON;
- controller: one tick = ONE timeline with the controller phases and the
  backend's phases nested under tick/decide;
- IncrementalDecider refresh audit: a forced mismatch increments
  ``escalator_tpu_incremental_audit_mismatch_total`` AND writes a dump
  artifact (the satellite contract);
- jax.monitoring bridge: compiles observed inside a tick land on the tick
  record and the Prometheus counters;
- inertness: instrumented entries' jaxprs are byte-identical to
  uninstrumented ones — spans live strictly outside traced code, so the R4
  host-callback ban (and every other jaxlint budget) is untouched by
  construction, not by luck.
"""

import json
import threading

import numpy as np
import pytest

from escalator_tpu import observability as obs
from escalator_tpu.metrics import metrics
from escalator_tpu.observability import flightrecorder, jaxmon, spans

from tests.test_controller import World, make_opts
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)


def _counter(name, labels=None):
    return metrics.registry.get_sample_value(name, labels or {}) or 0.0


# ---------------------------------------------------------------------- spans
def test_span_nesting_paths_and_fencing():
    with spans.span("root"):
        spans.annotate(backend="t1")
        with spans.span("pack"):
            pass
        with spans.span("decide", kind="device"):
            spans.fence(None)
        with spans.span("dispatch_only", kind="device"):
            pass  # never fenced: duration is dispatch time only
    rec = obs.RECORDER.last()
    assert rec["root"] == "root" and rec["backend"] == "t1"
    by_name = {p["name"]: p for p in rec["phases"]}
    assert by_name["pack"]["path"] == "root/pack"
    assert by_name["pack"]["fenced"] is True          # host: sync by nature
    assert by_name["decide"]["fenced"] is True        # device + fence()
    assert by_name["dispatch_only"]["fenced"] is False
    assert by_name["root"]["ms"] == rec["duration_ms"]
    assert all(p["ms"] >= 0 for p in rec["phases"])


def test_span_disabled_records_nothing():
    depth = obs.RECORDER.depth
    spans.set_enabled(False)
    try:
        with spans.span("ghost"):
            spans.annotate(backend="ghost")
            spans.add_phase("phantom", 1.0)
    finally:
        spans.set_enabled(True)
    assert obs.RECORDER.depth == depth
    assert (obs.RECORDER.last() or {}).get("root") != "ghost"


def test_span_thread_local_timelines():
    """Two threads ticking concurrently never interleave phases."""
    out = {}

    def worker(name):
        with spans.span(name):
            with spans.span("inner"):
                pass
        # find this thread's record
        rec = next(r for r in reversed(obs.RECORDER.snapshot())
                   if r["root"] == name)
        out[name] = rec

    ts = [threading.Thread(target=worker, args=(f"thr{i}",)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for name, rec in out.items():
        paths = {p["path"] for p in rec["phases"]}
        assert paths == {name, f"{name}/inner"}, paths


def test_graft_nests_remote_phases():
    with spans.span("local"):
        with spans.span("rpc", kind="rpc"):
            pass
        spans.graft(
            [{"name": "decide", "path": "server/decide", "ms": 2.0,
              "kind": "device", "fenced": True}],
            under="local/rpc")
    rec = obs.RECORDER.last()
    by_path = {p["path"]: p for p in rec["phases"]}
    assert by_path["local/rpc/server/decide"]["ms"] == 2.0
    assert by_path["local/rpc/server/decide"]["fenced"] is True


def test_recorder_ring_is_bounded_and_dump_is_json(tmp_path):
    rec = flightrecorder.FlightRecorder(capacity=4)
    for i in range(10):
        tl = spans.Timeline(name=f"t{i}", wall_time=0.0, t0=0.0)
        tl.duration_sec = 0.001
        rec.record_timeline(tl)
    assert rec.depth == 4
    assert rec.total_recorded == 10
    assert [r["root"] for r in rec.snapshot()] == ["t6", "t7", "t8", "t9"]
    path = rec.dump(str(tmp_path / "dump.json"), reason="test")
    doc = json.loads(open(path).read())
    assert doc["flight_recorder"] and doc["reason"] == "test"
    assert doc["depth"] == 4 and len(doc["ticks"]) == 4


# ------------------------------------------------------- backend tick records
def _world(backend, **kw):
    pods = build_test_pods(10, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key="customer", node_selector_value="buildeng"))
    nodes = build_test_nodes(4, NodeOpts(cpu=1000, mem=4 * 10**9))
    return World(make_opts(), nodes=nodes, pods=pods, backend=backend, **kw)


BACKENDS = [
    ("golden", lambda: __import__(
        "escalator_tpu.controller.backend", fromlist=["GoldenBackend"]
    ).GoldenBackend()),
    ("jax", lambda: __import__(
        "escalator_tpu.controller.backend", fromlist=["JaxBackend"]
    ).JaxBackend()),
    ("incremental-jax", lambda: __import__(
        "escalator_tpu.controller.backend", fromlist=["IncrementalJaxBackend"]
    ).IncrementalJaxBackend()),
    ("sharded-jax", lambda: __import__(
        "escalator_tpu.controller.backend", fromlist=["ShardedJaxBackend"]
    ).ShardedJaxBackend()),
    ("podaxis-jax", lambda: __import__(
        "escalator_tpu.controller.backend", fromlist=["PodAxisJaxBackend"]
    ).PodAxisJaxBackend()),
]


@pytest.mark.parametrize("name,make", BACKENDS, ids=[b[0] for b in BACKENDS])
def test_every_backend_tick_records_four_fenced_phases(name, make):
    """The acceptance bar: every backend's tick lands in the flight recorder
    with >= 4 named, device-fenced phases, nested under the controller's
    tick root, carrying backend/impl/digest annotations."""
    w = _world(make())
    w.tick()
    rec = obs.RECORDER.last()
    assert rec["root"] == "tick"
    assert rec["backend"] == name
    assert "impl" in rec and "digest" in rec
    # controller phases present
    names = {p["name"] for p in rec["phases"]}
    assert {"provider_refresh", "group_scan", "decide", "act"} <= names
    # backend phases nest under tick/decide/<backend>/...
    backend_phases = [
        p for p in rec["phases"]
        if p["path"].startswith(f"tick/decide/{name}/")
    ]
    fenced = [p for p in backend_phases if p["fenced"]]
    assert len({p["name"] for p in fenced}) >= 4, (
        sorted(p["path"] for p in rec["phases"]))
    # per-phase Prometheus histograms observed under this backend label —
    # LEAF phases only (composites like the backend's decide envelope stay
    # recorder-only; their nested decide_light/decide_ordered carry the
    # series), so probe a known leaf
    leaf = "evaluate" if name == "golden" else "pack"
    assert _counter("escalator_tpu_tick_phase_seconds_count",
                    {"backend": name, "phase": leaf}) > 0
    # the composite decide envelope must NOT be observed (it would double-
    # count its nested decide_light/decide_ordered under one series)
    assert metrics.registry.get_sample_value(
        "escalator_tpu_tick_phase_seconds_count",
        {"backend": name, "phase": "decide"}) is None


def test_native_backend_tick_records_fenced_phases():
    from escalator_tpu.controller.native_backend import make_native_backend

    w = _world(make_native_backend)
    w.tick()
    rec = obs.RECORDER.last()
    assert rec["backend"] == "native-jax"
    backend_phases = [
        p for p in rec["phases"]
        if p["path"].startswith("tick/decide/native-jax/")
    ]
    fenced_names = {p["name"] for p in backend_phases if p["fenced"]}
    # round 12: the old host_snapshot composite is split into the streaming
    # taxonomy — event_drain (store dirty drain + triple gather) and
    # triple_build (the remaining [G]/[N] host assembly)
    assert {"event_drain", "triple_build", "scatter", "decide",
            "unpack"} <= fenced_names
    assert rec.get("store") in ("native", "numpy")


def test_incremental_backend_records_delta_phase_and_dirty_count():
    from escalator_tpu.controller.backend import IncrementalJaxBackend

    w = _world(IncrementalJaxBackend())
    w.tick()   # rebuild + full decide seeds the columns
    w.tick()   # steady tick: host-diff -> scatter -> delta_decide
    rec = obs.RECORDER.last()
    names = {p["name"] for p in rec["phases"]}
    assert {"host_diff", "scatter", "delta_decide"} <= names, sorted(names)
    assert rec.get("dirty_groups") is not None


def test_digest_stable_for_identical_inputs_changes_on_different():
    from escalator_tpu.core import semantics as sem
    from escalator_tpu.controller.backend import JaxBackend

    backend = JaxBackend()
    cfg = sem.GroupConfig(
        min_nodes=0, max_nodes=100, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=70,
        slow_removal_rate=1, fast_removal_rate=2,
    )
    pods = build_test_pods(6, PodOpts(cpu=[500], mem=[10**8]))
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    gi = [(pods, nodes, cfg, sem.GroupState())]
    backend.decide(gi, 1_700_000_000)
    d1 = obs.RECORDER.last()["digest"]
    backend.decide(gi, 1_700_000_000)
    d2 = obs.RECORDER.last()["digest"]
    assert d1 == d2          # same inputs -> same decision -> same digest
    backend.decide([(pods[:1], nodes, cfg, sem.GroupState())], 1_700_000_000)
    assert obs.RECORDER.last()["digest"] != d1   # decision changed


# -------------------------------------------------- audit mismatch satellite
def test_audit_mismatch_counts_and_dumps(tmp_path, monkeypatch):
    """Forcing an incremental-aggregate divergence must increment the
    mismatch counter AND write a flight-record dump artifact (repair mode —
    the alertable path the backend-mode silent repair lacked)."""
    import random

    from escalator_tpu.core.arrays import pack_cluster
    from escalator_tpu.ops.device_state import (
        AggregateParityError,
        DeviceClusterCache,
        IncrementalDecider,
    )
    from tests.test_kernel_parity import random_group

    monkeypatch.setenv("ESCALATOR_TPU_FLIGHT_DUMP_DIR", str(tmp_path))
    rng = random.Random(5)
    cluster = pack_cluster([random_group(rng, gi) for gi in range(4)],
                           pad_pods=128, pad_nodes=64, pad_groups=8)
    cache = DeviceClusterCache(cluster)
    inc = IncrementalDecider(cache, refresh_every=0, on_mismatch="repair")
    inc.decide(np.int64(1_700_000_000), False)
    # corrupt the resident state BEHIND the aggregate maintenance: a plain
    # scatter (no aggregate fold) of one changed pod lane
    pods = cluster.pods
    changed = type(pods)(**{
        f: np.array(getattr(pods, f)) for f in pods.__dataclass_fields__})
    changed.cpu_milli[0] = changed.cpu_milli[0] + 777
    cache.set_host(changed, cluster.nodes)
    cache.apply_gathered(cache.gather_deltas(
        np.array([0], np.int64), np.empty(0, np.int64)))
    before = _counter("escalator_tpu_incremental_audit_mismatch_total")
    assert inc.refresh() is False          # repaired, not raised
    assert _counter(
        "escalator_tpu_incremental_audit_mismatch_total") == before + 1
    dumps = list(tmp_path.glob("escalator-tpu-flight-audit-mismatch-*.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["reason"] == "audit-mismatch" and doc["flight_recorder"]
    # raise mode ALSO counts + dumps before raising
    cache2 = DeviceClusterCache(cluster)
    inc2 = IncrementalDecider(cache2, refresh_every=0, on_mismatch="raise")
    inc2.decide(np.int64(1_700_000_000), False)
    cache2.set_host(changed, cluster.nodes)
    cache2.apply_gathered(cache2.gather_deltas(
        np.array([0], np.int64), np.empty(0, np.int64)))
    with pytest.raises(AggregateParityError):
        inc2.refresh()
    assert _counter(
        "escalator_tpu_incremental_audit_mismatch_total") == before + 2
    assert len(list(
        tmp_path.glob("escalator-tpu-flight-audit-mismatch-*.json"))) == 2


# ------------------------------------------------------------ jaxmon bridge
def test_jaxmon_counts_compiles_into_tick_records():
    import jax
    import jax.numpy as jnp

    assert jaxmon.install()   # idempotent; jax is loaded in this suite
    marker = float(np.random.default_rng(99).integers(1, 1 << 30))
    fn = jax.jit(lambda x: x * marker + 1.5)   # never-seen shape+closure

    with spans.span("compile_tick"):
        with spans.span("compute", kind="device"):
            spans.fence(fn(jnp.ones(7)))       # forces a backend compile
    rec = obs.RECORDER.last()
    assert rec["root"] == "compile_tick"
    assert rec["compile_events"] >= 1
    assert rec["compile_seconds"] > 0
    assert _counter("escalator_tpu_jax_compile_events_total") >= 1
    # a tick re-dispatching the SAME program records zero compiles — the
    # steady-state signal a retrace storm would break
    with spans.span("warm_tick"):
        with spans.span("compute", kind="device"):
            spans.fence(fn(jnp.ones(7)))
    assert obs.RECORDER.last()["compile_events"] == 0


# -------------------------------------------------------------- inertness
def test_instrumented_jaxprs_byte_identical():
    """Spans live strictly OUTSIDE traced code: tracing a registry entry
    with recording active (inside a span, recorder on) yields a jaxpr
    byte-identical to recording disabled — so every jaxlint budget (R4 host
    callbacks included) is structurally untouched by instrumentation."""
    import jax

    from escalator_tpu.analysis.registry import default_registry

    entries = {e.name: e for e in default_registry()}
    for name in ("kernel.decide", "kernel.delta_decide"):
        traced = entries[name].build()

        def jaxpr_text():
            return str(jax.make_jaxpr(traced.fn)(*traced.args))

        spans.set_enabled(False)
        try:
            plain = jaxpr_text()
        finally:
            spans.set_enabled(True)
        with spans.span("instrumented_trace"):
            instrumented = jaxpr_text()
        assert instrumented == plain, f"{name}: jaxpr changed under spans"


# ------------------------------------------------------------- incident dump
def test_dump_on_incident_writes_and_counts(tmp_path, monkeypatch):
    target = tmp_path / "dumps"
    target.mkdir()
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(target))
    before = _counter("escalator_tpu_flight_recorder_dumps_total",
                      {"reason": "wedge"})
    path = obs.dump_on_incident("wedge")
    assert path is not None and json.loads(open(path).read())["reason"] == "wedge"
    assert path.startswith(str(target)), path
    assert _counter("escalator_tpu_flight_recorder_dumps_total",
                    {"reason": "wedge"}) == before + 1
    # unwritable dir: returns None, never raises (incident path safety)
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR",
                       str(tmp_path / "missing" / "deeper"))
    assert obs.dump_on_incident("wedge") is None


def test_dump_dir_legacy_alias_still_honored(tmp_path, monkeypatch):
    """The pre-round-10 ESCALATOR_TPU_FLIGHT_DUMP_DIR spelling keeps working
    when the new ESCALATOR_TPU_DUMP_DIR is unset (compat contract)."""
    legacy = tmp_path / "legacy"
    legacy.mkdir()
    monkeypatch.delenv("ESCALATOR_TPU_DUMP_DIR", raising=False)
    monkeypatch.setenv("ESCALATOR_TPU_FLIGHT_DUMP_DIR", str(legacy))
    path = obs.dump_on_incident("wedge")
    assert path is not None and path.startswith(str(legacy)), path
    # and the new env takes precedence over the legacy one when both are set
    newer = tmp_path / "newer"
    newer.mkdir()
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(newer))
    path2 = obs.dump_on_incident("wedge")
    assert path2 is not None and path2.startswith(str(newer)), path2
