"""Config schema + validation + filter tests, ported from the reference's
node_group_test.go tables."""

import pytest

from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.k8s import types as k8s
from escalator_tpu.testsupport.builders import PodOpts, build_test_pod

VALID_YAML = """
node_groups:
  - name: "shared"
    label_key: "customer"
    label_value: "shared"
    cloud_provider_group_name: "shared-nodes"
    min_nodes: 1
    max_nodes: 30
    dry_mode: false
    taint_upper_capacity_threshold_percent: 40
    taint_lower_capacity_threshold_percent: 10
    scale_up_threshold_percent: 70
    slow_node_removal_rate: 2
    fast_node_removal_rate: 5
    soft_delete_grace_period: 1m
    hard_delete_grace_period: 10m
    scale_up_cool_down_period: 2m
    taint_effect: NoExecute
    aws:
      launch_template_id: lt-123
      launch_template_version: "2"
      lifecycle: spot
      instance_type_overrides: ["m5.large", "m5.xlarge"]
      resource_tagging: true
  - name: "default"
    label_key: "customer"
    label_value: "buildeng"
    cloud_provider_group_name: "buildeng-nodes"
    min_nodes: 1
    max_nodes: 10
    taint_upper_capacity_threshold_percent: 40
    taint_lower_capacity_threshold_percent: 10
    scale_up_threshold_percent: 70
    slow_node_removal_rate: 1
    fast_node_removal_rate: 2
    soft_delete_grace_period: 30s
    hard_delete_grace_period: 1m30s
    scale_up_cool_down_period: 2m
"""


class TestUnmarshal:
    def test_parse(self):
        groups = ngmod.unmarshal_node_group_options(VALID_YAML)
        assert len(groups) == 2
        g = groups[0]
        assert g.name == "shared"
        assert g.min_nodes == 1 and g.max_nodes == 30
        assert g.taint_effect == "NoExecute"
        assert g.aws.launch_template_id == "lt-123"
        assert g.aws.lifecycle == "spot"
        assert g.aws.instance_type_overrides == ["m5.large", "m5.xlarge"]
        assert g.aws.resource_tagging is True
        assert groups[1].hard_delete_grace_period_duration() == 90.0

    def test_hard_delete_yaml_tag_fixed(self):
        """The reference drops hard_delete_grace_period from YAML due to a wrong
        struct tag (node_group.go:40). We parse it correctly — deliberate fix."""
        g = ngmod.unmarshal_node_group_options(VALID_YAML)[0]
        assert g.hard_delete_grace_period == "10m"
        assert g.hard_delete_grace_period_duration() == 600.0

    def test_validate_ok(self):
        for g in ngmod.unmarshal_node_group_options(VALID_YAML):
            assert ngmod.validate_node_group(g) == []

    def test_unknown_fields_ignored(self):
        g = ngmod.unmarshal_node_group_options(
            "node_groups:\n  - name: x\n    bogus_field: 1\n"
        )
        assert g[0].name == "x"


class TestDurations:
    @pytest.mark.parametrize("s,want", [
        ("300ms", 0.3), ("10s", 10.0), ("2m", 120.0), ("1.5h", 5400.0),
        ("2h45m", 9900.0), ("1m30s", 90.0), ("", 0.0), ("bogus", 0.0),
        ("-5s", -5.0),
    ])
    def test_parse(self, s, want):
        assert ngmod.parse_duration(s) == want


class TestValidation:
    def _valid(self):
        return ngmod.unmarshal_node_group_options(VALID_YAML)[0]

    def test_ordering_violations(self):
        g = self._valid()
        g.taint_lower_capacity_threshold_percent = 50
        problems = ngmod.validate_node_group(g)
        assert any("taint_lower" in p for p in problems)

        g = self._valid()
        g.scale_up_threshold_percent = 30
        problems = ngmod.validate_node_group(g)
        assert any("taint_upper" in p for p in problems)

    def test_min_max(self):
        g = self._valid()
        g.min_nodes, g.max_nodes = 30, 10
        assert any("min_nodes" in p for p in ngmod.validate_node_group(g))

    def test_auto_discovery_allows_zero_min_max(self):
        g = self._valid()
        g.min_nodes = g.max_nodes = 0
        assert g.auto_discover_min_max_node_options()
        assert ngmod.validate_node_group(g) == []

    def test_grace_ordering(self):
        g = self._valid()
        g.soft_delete_grace_period, g.hard_delete_grace_period = "10m", "1m"
        assert any("soft_delete" in p for p in ngmod.validate_node_group(g))

    def test_removal_rate_ordering(self):
        g = self._valid()
        g.slow_node_removal_rate, g.fast_node_removal_rate = 5, 2
        assert any("removal_rate" in p for p in ngmod.validate_node_group(g))

    def test_taint_effect(self):
        g = self._valid()
        g.taint_effect = "EvictPlz"
        assert any("taint_effect" in p for p in ngmod.validate_node_group(g))
        g.taint_effect = ""
        assert ngmod.validate_node_group(g) == []

    def test_aws_lifecycle(self):
        g = self._valid()
        g.aws.lifecycle = "weird"
        assert any("lifecycle" in p for p in ngmod.validate_node_group(g))


class TestFilters:
    def test_affinity_filter_selector_match(self):
        f = ngmod.new_pod_affinity_filter_func("customer", "shared")
        assert f(build_test_pod(PodOpts(
            cpu=[1], mem=[1],
            node_selector_key="customer", node_selector_value="shared")))
        assert not f(build_test_pod(PodOpts(
            cpu=[1], mem=[1],
            node_selector_key="customer", node_selector_value="other")))
        # daemonsets excluded even when matching
        assert not f(build_test_pod(PodOpts(
            cpu=[1], mem=[1], owner="DaemonSet",
            node_selector_key="customer", node_selector_value="shared")))

    def test_affinity_filter_affinity_match(self):
        f = ngmod.new_pod_affinity_filter_func("customer", "shared")
        assert f(build_test_pod(PodOpts(
            cpu=[1], mem=[1],
            node_affinity_key="customer", node_affinity_value="shared")))
        # NotIn operator unsupported -> no match (reference: node_group.go:241)
        assert not f(build_test_pod(PodOpts(
            cpu=[1], mem=[1],
            node_affinity_key="customer", node_affinity_value="shared",
            node_affinity_op="NotIn")))

    def test_default_filter(self):
        f = ngmod.new_pod_default_filter_func()
        assert f(build_test_pod(PodOpts(cpu=[1], mem=[1])))
        assert not f(build_test_pod(PodOpts(cpu=[1], mem=[1], owner="DaemonSet")))
        assert not f(build_test_pod(PodOpts(cpu=[1], mem=[1], static=True)))
        assert not f(build_test_pod(PodOpts(
            cpu=[1], mem=[1],
            node_selector_key="customer", node_selector_value="x")))
        assert not f(build_test_pod(PodOpts(
            cpu=[1], mem=[1],
            node_affinity_key="customer", node_affinity_value="x")))

    def test_node_label_filter(self):
        f = ngmod.new_node_label_filter_func("customer", "shared")
        assert f(k8s.Node(name="a", labels={"customer": "shared"}))
        assert not f(k8s.Node(name="b", labels={"customer": "other"}))
        assert not f(k8s.Node(name="c"))
