"""jaxlint gate: zero findings on the clean tree, and PROOF the rules detect
the regression classes they were built for.

The zero-findings half is the CI invariant (`make analyze` blocks on it).
The mutation half re-introduces each hazard the hard way — the actual
legacy replicated sort, an actually-dropped donate_argnums, an actual f32
cast on a parity output — and asserts the expected rule fires. A lint gate
whose detections are untested is a gate that rots silently.
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from escalator_tpu.analysis import (  # noqa: E402
    KernelEntry,
    TracedEntry,
    analyze_entry,
    default_registry,
    run_analysis,
)
from escalator_tpu.analysis.registry import (  # noqa: E402
    DECISION_DTYPES,
    NODES,
    NOW,
    PODS,
    representative_cluster,
)
from escalator_tpu.analysis import registry as reg  # noqa: E402
from escalator_tpu.analysis.rules import apply_waivers, Finding  # noqa: E402
from escalator_tpu.analysis.walker import count_primitives, iter_sites  # noqa: E402


def _rules_of(report):
    return sorted({f.rule for f in report.findings})


# ---------------------------------------------------------------------------
# The gate: clean tree -> zero unwaived findings
# ---------------------------------------------------------------------------


def test_clean_tree_has_zero_unwaived_findings():
    report = run_analysis()
    unwaived = report.unwaived
    assert not unwaived, "\n".join(
        f"{f.rule} {f.entry}: {f.summary} ({f.detail})" for f in unwaived
    )
    assert report.x64_enabled
    # every entry actually ran: the gate is meaningless if the mesh entries
    # silently skipped (conftest pins 8 virtual devices exactly for this)
    skipped = [e.name for e in report.entries if e.status == "skipped"]
    assert not skipped, f"entries skipped on the 8-device test rig: {skipped}"


def test_legacy_replicated_path_is_waived_not_clean():
    """The legacy full-[N]-sort podaxis program must be VISIBLE as a waived
    R1 finding — if it ever disappears (path deleted or sort sharded), the
    waiver ledger is stale and should be pruned."""
    report = run_analysis(with_retrace=False)
    legacy = [
        f for f in report.findings
        if f.entry == "podaxis.decider_legacy_replicated" and f.rule == "R1"
    ]
    assert legacy, "legacy replicated entry no longer produces the R1 "\
                   "finding; remove its waiver from analysis/waivers.py"
    assert all(f.waived for f in legacy)


def test_registry_covers_every_kernel_module():
    covered = {e.module for e in default_registry()}
    for required in (
        "escalator_tpu.ops.kernel",
        "escalator_tpu.ops.order_tail",
        "escalator_tpu.ops.binpack",
        "escalator_tpu.ops.device_state",
        "escalator_tpu.ops.simulate",
        "escalator_tpu.parallel.grid",
        "escalator_tpu.parallel.podaxis",
        "escalator_tpu.parallel.mesh",
    ):
        assert required in covered, f"no registry entry for {required}"


# ---------------------------------------------------------------------------
# Mutation tests: each hazard class, re-introduced, must be detected
# ---------------------------------------------------------------------------


def test_mutation_replicated_sort_fires_R1():
    """Re-introduce the PR-1 busy-tail bug class: the podaxis ordered
    decider WITHOUT node_blocks full-sorts [N] on every device. Registered
    without its waiver, R1 must fire."""
    entry = KernelEntry(
        name="mutation.replicated_sort",
        module="test", kind="shard_map",
        build=reg._build_podaxis_legacy,
        mapped=True, min_devices=8,
        global_axes={"pods": PODS, "nodes": NODES},
    )
    report = analyze_entry(entry, with_retrace=False)
    assert "R1" in _rules_of(report)
    r1 = [f for f in report.findings if f.rule == "R1"]
    assert any("nodes" in f.summary for f in r1)


def test_mutation_dropped_donation_fires_R5():
    """jit the scatter body WITHOUT donate_argnums — the refactor that
    silently turns the O(changes) resident update into O(cluster) traffic."""
    from escalator_tpu.ops import device_state as ds

    def build():
        t = reg._build_scatter_update()
        return TracedEntry(fn=t.fn, args=t.args, jitted=jax.jit(ds._scatter_body))

    entry = KernelEntry(
        name="mutation.no_donate", module="test", kind="jit",
        build=build, donate_expected=True,
    )
    report = analyze_entry(entry, with_retrace=False)
    assert _rules_of(report) == ["R5"]


def test_mutation_f32_demotion_fires_R2():
    """Cast a parity-critical float64 output to f32: both halves of R2 (the
    declared contract and the mid-program demotion scan) must fire."""
    from escalator_tpu.ops import kernel

    def build():
        cluster = representative_cluster()

        def fn(c, t):
            out = kernel.decide(c, t)
            return dataclasses.replace(
                out, cpu_percent=out.cpu_percent.astype(jnp.float32)
            )

        return TracedEntry(fn=fn, args=(cluster, NOW))

    entry = KernelEntry(
        name="mutation.f32_demotion", module="test", kind="jit",
        build=build, output_dtypes=DECISION_DTYPES,
    )
    report = analyze_entry(entry, with_retrace=False)
    r2 = [f for f in report.findings if f.rule == "R2"]
    assert any("cpu_percent" in f.summary for f in r2), report.findings
    assert any("demoted" in f.summary for f in r2), report.findings


def test_mutation_new_collective_fires_R3():
    """Pin a budget below the traced count: the 'new psum on the hot path'
    tripwire."""
    entry = KernelEntry(
        name="mutation.collective_creep", module="test", kind="shard_map",
        build=reg._build_podaxis_light, mapped=True, min_devices=8,
        collective_budget=0,  # the light decider legitimately has 1
    )
    report = analyze_entry(entry, with_retrace=False)
    assert "R3" in _rules_of(report)


def test_mutation_host_callback_fires_R4():
    def build():
        def fn(x):
            jax.debug.callback(lambda v: None, x[0])
            return x * 2

        return TracedEntry(fn=fn, args=(np.arange(8.0),))

    entry = KernelEntry(
        name="mutation.host_callback", module="test", kind="jit", build=build,
    )
    report = analyze_entry(entry, with_retrace=False)
    assert "R4" in _rules_of(report)


def test_mutation_stray_host_scalar_fires_R7():
    """Feed a python float back into a jitted callee at execute time — the
    'stray float(x) on the dispatch path' bug class. Under the transfer
    guard the implicit host->device transfer is an error R7 reports."""
    def build():
        body = jax.jit(lambda x, s: x * s)

        def fn(x):
            return body(x, 2.0)

        def execute(placed):
            (x,) = placed
            return body(x, float(np.asarray(x)[0]))  # host scalar re-fed

        return TracedEntry(fn=fn, args=(np.arange(8.0),), execute=execute)

    entry = KernelEntry(
        name="mutation.stray_host_scalar", module="test", kind="jit",
        build=build,
    )
    report = analyze_entry(entry, with_retrace=False, with_execute=True)
    r7 = [f for f in report.findings if f.rule == "R7"]
    assert r7, report.findings
    assert "host-to-device" in r7[0].detail or "host_to_device" in r7[0].detail

    # the same entry with a declared escape hatch is clean
    allowed = dataclasses.replace(entry, name="mutation.allowed_transfer",
                                  transfer_allow=("host_to_device",))
    report = analyze_entry(allowed, with_retrace=False, with_execute=True)
    assert not [f for f in report.findings if f.rule == "R7"]


def test_mutation_unknown_transfer_allow_direction_fires_R7():
    entry = KernelEntry(
        name="mutation.bad_direction", module="test", kind="jit",
        build=lambda: TracedEntry(fn=lambda x: x * 2, args=(np.arange(4.0),)),
        transfer_allow=("host_to_devize",),
    )
    report = analyze_entry(entry, with_retrace=False, with_execute=True)
    r7 = [f for f in report.findings if f.rule == "R7"]
    assert r7 and "host_to_devize" in r7[0].summary


def test_mutation_callback_in_overlap_span_fires_R8():
    """A host callback smuggled into a kernel that the host path overlaps
    with prep: the lowered module grows a host-sync custom call, which
    would serialize the span the overlap machinery assumes is fenceless."""
    def build():
        def fn(x):
            jax.debug.callback(lambda v: None, x[0])
            return x * 2

        return TracedEntry(fn=fn, args=(np.arange(8.0),),
                           jitted=jax.jit(fn))

    entry = KernelEntry(
        name="mutation.sync_in_span", module="test", kind="jit",
        build=build, overlap_span="decide",
    )
    report = analyze_entry(entry, with_retrace=False)
    r8 = [f for f in report.findings if f.rule == "R8"]
    assert r8, report.findings
    assert "decide" in r8[0].summary
    # without the overlap_span declaration R8 does not apply (R4 still
    # catches the callback itself)
    plain = dataclasses.replace(entry, name="mutation.no_span",
                                overlap_span=None)
    report = analyze_entry(plain, with_retrace=False)
    assert not [f for f in report.findings if f.rule == "R8"]


@pytest.mark.slow
def test_full_registry_transfer_hygiene_is_clean():
    """R7 over the whole registry: every entry executes under the transfer
    guard without an unwaived finding. Slow-marked — this actually compiles
    and runs all 32 entries."""
    report = run_analysis(with_retrace=False, with_execute=True)
    unwaived = report.unwaived
    assert not unwaived, "\n".join(
        f"{f.rule} {f.entry}: {f.summary} ({f.detail})" for f in unwaived
    )


# ---------------------------------------------------------------------------
# Walker + waiver mechanics
# ---------------------------------------------------------------------------


def test_walker_descends_into_control_flow():
    def fn(x):
        return jax.lax.cond(
            x.sum() > 0, lambda a: jnp.sort(a), lambda a: a, x
        )

    closed = jax.make_jaxpr(fn)(np.arange(8.0))
    counts = count_primitives(closed)
    assert counts.get("sort", 0) == 1  # the sort lives inside a cond branch


def test_walker_tags_mapped_context_and_axes():
    traced = reg._build_podaxis_blocks()
    closed = jax.make_jaxpr(traced.fn)(*traced.args)
    mapped_sites = [s for s in iter_sites(closed) if s.mapped]
    assert mapped_sites, "no sites tagged as inside shard_map"
    psums = [s for s in mapped_sites if s.primitive in ("psum", "psum2")]
    assert psums
    for s in psums:
        assert s.bound_axes, "psum site lost its bound mesh axes"


def test_waiver_matching_is_rule_and_entry_scoped():
    findings = [
        Finding(rule="R1", entry="podaxis.decider_legacy_replicated",
                summary="s"),
        Finding(rule="R5", entry="podaxis.decider_legacy_replicated",
                summary="s"),
        Finding(rule="R1", entry="grid.decider", summary="s"),
    ]
    apply_waivers(findings, [{
        "rule": "R1", "entry": "podaxis.*", "reason": "test",
    }])
    assert [f.waived for f in findings] == [True, False, False]


def test_external_waiver_file_roundtrip(tmp_path):
    import json

    from escalator_tpu.analysis import load_waivers

    path = tmp_path / "waivers.json"
    path.write_text(json.dumps([
        {"rule": "R3", "entry": "mutation.*", "reason": "testing"},
    ]))
    waivers = load_waivers(str(path))
    entry = KernelEntry(
        name="mutation.collective_creep", module="test", kind="shard_map",
        build=reg._build_podaxis_light, mapped=True, min_devices=8,
        collective_budget=0,
    )
    report = run_analysis(entries=[entry], extra_waivers=waivers,
                          with_retrace=False)
    assert report.findings and not report.unwaived

    path.write_text(json.dumps([{"rule": "R3"}]))  # missing keys
    with pytest.raises(ValueError):
        load_waivers(str(path))
