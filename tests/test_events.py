"""k8s Events on scaling actions — the analog of the reference's event
broadcaster (/root/reference/cmd/main.go:166-170). The in-memory client records
them (real adapters forward to the apiserver); dry mode must leave no trace."""

from escalator_tpu.controller.backend import GoldenBackend
from escalator_tpu.k8s import types as k8s
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)

from tests.test_controller import LABEL_KEY, LABEL_VALUE, World, make_opts


def _reasons(w):
    return [e.reason for e in w.client.events]


def _scale_up_world(dry_mode=False):
    pods = build_test_pods(10, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    return World(make_opts(), nodes=nodes, pods=pods, backend=GoldenBackend(),
                 dry_mode=dry_mode)


def test_scale_up_records_event():
    w = _scale_up_world()
    w.tick()
    assert "ScaleUpCloudProvider" in _reasons(w)
    (ev,) = [e for e in w.client.events if e.reason == "ScaleUpCloudProvider"]
    assert ev.involved_kind == "NodeGroup"
    assert ev.involved_name == "buildeng"
    assert "by 6" in ev.message
    assert ev.type == "Normal"


def test_scale_down_taint_records_event():
    pods = build_test_pods(1, PodOpts(
        cpu=[100], mem=[10**8],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    nodes = build_test_nodes(10, NodeOpts(cpu=4000, mem=16 * 10**9))
    w = World(make_opts(), nodes=nodes, pods=pods, backend=GoldenBackend())
    w.tick()
    assert "ScaleDownTaint" in _reasons(w)


def test_reaper_records_delete_event():
    pods = []
    nodes = build_test_nodes(4, NodeOpts(cpu=4000, mem=16 * 10**9))
    # two nodes long-tainted and empty -> reap-eligible; min_nodes=1 keeps others
    w = World(make_opts(min_nodes=0), nodes=nodes, pods=pods,
              backend=GoldenBackend())
    for n in w.client.list_nodes()[:2]:
        n.taints.append(k8s.Taint(
            key=k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY,
            value=str(int(w.clock.now()) - 10_000),
        ))
        w.client.update_node(n)
    w.tick()
    assert "DeleteNodes" in _reasons(w)
    (ev,) = [e for e in w.client.events if e.reason == "DeleteNodes"]
    assert "2 expired" in ev.message


def test_dry_mode_records_nothing():
    w = _scale_up_world(dry_mode=True)
    w.tick()
    assert w.client.events == []


def test_repeat_events_compact_to_count():
    """(reason, object) repeats bump count instead of growing the event list
    unboundedly — apiserver event-series semantics. The message is NOT part of
    the key (emitted messages embed counts like 'increased by 3', so keying on
    text would never compact); the freshest text wins."""
    from escalator_tpu.k8s.client import InMemoryKubernetesClient

    c = InMemoryKubernetesClient()
    for ts in (100, 160):
        c.create_event(k8s.Event(
            reason="ScaleUpCloudProvider", message="increased by 3",
            involved_name="buildeng", timestamp_sec=ts,
        ))
    c.create_event(k8s.Event(
        reason="ScaleUpCloudProvider", message="increased by 5",
        involved_name="buildeng", timestamp_sec=200,
    ))
    assert len(c.events) == 1
    assert c.events[0].count == 3
    assert c.events[0].timestamp_sec == 200
    assert c.events[0].message == "increased by 5"
    # a different object does NOT compact
    c.create_event(k8s.Event(
        reason="ScaleUpCloudProvider", message="increased by 1",
        involved_name="other-group", timestamp_sec=210,
    ))
    assert len(c.events) == 2


def test_event_list_is_capped():
    from escalator_tpu.k8s.client import InMemoryKubernetesClient

    c = InMemoryKubernetesClient()
    for i in range(c.MAX_EVENTS + 50):
        c.create_event(k8s.Event(
            reason="R", message=f"m{i}", involved_name=f"g{i}",
            timestamp_sec=i,
        ))
    assert len(c.events) == c.MAX_EVENTS
    assert c.events[-1].involved_name == f"g{c.MAX_EVENTS + 49}"
