"""Randomized multi-tick parity soak for the incremental decide (round 8).

The tentpole's contract is unforgiving: a :class:`GroupAggregates`
maintained by scatter deltas must stay BIT-equal to a from-scratch
recompute, and ``kernel.delta_decide`` on the compacted dirty rows must be
bit-identical to a full ``decide_jit`` on the same resident cluster — on
every tick of an arbitrary churn sequence, on both the lazy (light) and
ordered paths. These tests drive seeded sequences of pod upserts/deletes,
node add/remove (with slot reuse), taint/untaint/cordon flips, group
config/state mutations and group add/remove through the real native store +
``DeviceClusterCache`` + ``IncrementalDecider`` stack and compare against
the full-recompute kernel after EVERY tick. The sharded variants
(grid per-block delta decider, pod-axis delta scatter) get the same
bit-equality treatment at their layouts.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from escalator_tpu.analysis.registry import (  # noqa: E402
    representative_cluster,
    stacked_cluster,
)
from escalator_tpu.core.arrays import NO_TAINT_TIME, ClusterArrays  # noqa: E402
from escalator_tpu.ops import kernel  # noqa: E402
from escalator_tpu.ops.device_state import (  # noqa: E402
    AggregateParityError,
    DeviceClusterCache,
    IncrementalDecider,
)

NOW = 1_700_000_000


def _assert_decisions_equal(got, want, context=""):
    for f in dataclasses.fields(want):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f.name)), np.asarray(getattr(want, f.name)),
            err_msg=f"{context}: field {f.name}",
        )


def _assert_aggs_equal(got, want, context=""):
    for f in dataclasses.fields(kernel.GroupAggregates):
        if f.name == "dirty":
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f.name)), np.asarray(getattr(want, f.name)),
            err_msg=f"{context}: aggregate {f.name}",
        )


def _store_world(seed: int, G: int = 8):
    """Native store + device cache + incremental decider over a small
    churning cluster; groups ride in from a representative GroupArrays."""
    from escalator_tpu.native.statestore import NativeStateStore

    rng = np.random.default_rng(seed)
    store = NativeStateStore(pod_capacity=1 << 9, node_capacity=1 << 7)
    store.upsert_pods_batch(
        [f"p{i}" for i in range(180)], rng.integers(0, G, 180),
        np.full(180, 500), np.full(180, 10**9),
    )
    store.upsert_nodes_batch(
        [f"n{i}" for i in range(40)], rng.integers(0, G, 40),
        np.full(40, 4000), np.full(40, 16 * 10**9),
        creation_ns=rng.integers(1, 10**12, 40),
    )
    pods_v, nodes_v = store.as_pod_node_arrays()
    groups = representative_cluster(G=G, P=1, N=1, seed=seed).groups
    store.drain_dirty()
    cache = DeviceClusterCache(
        ClusterArrays(groups=groups, pods=pods_v, nodes=nodes_v))
    return rng, store, groups, cache


def _random_churn(rng, store, groups, t, G):
    """One tick's randomized mutations across every event class. Mutates the
    host GroupArrays in place (config/state churn) and returns nothing —
    dirtiness flows through the store's drain + the group-row compare."""
    n = int(rng.integers(1, 25))
    idx = rng.integers(0, 180, n)
    store.upsert_pods_batch(
        [f"p{i}" for i in idx], idx % G,
        rng.integers(100, 2000, n), rng.integers(10**8, 2 * 10**9, n),
        node_slot=rng.integers(-1, 40, n),
    )
    if rng.random() < 0.3:
        store.delete_pod(f"p{int(rng.integers(0, 180))}")
    if rng.random() < 0.4:
        # node churn: capacity/taint/cordon flips, occasionally a group move
        # (exercises the node-group-changed pods-remaining re-sweep)
        ni = int(rng.integers(0, 40))
        tainted = bool(rng.random() < 0.5)
        store.upsert_node(
            f"n{ni}", int(rng.integers(0, G)) if rng.random() < 0.2 else ni % G,
            4000, 16 * 10**9,
            creation_ns=int(rng.integers(1, 10**12)),
            tainted=tainted,
            cordoned=bool(rng.random() < 0.2),
            taint_time_sec=(NOW - int(rng.integers(0, 2000))
                            if tainted else NO_TAINT_TIME),
        )
    if rng.random() < 0.25:
        store.delete_node(f"n{int(rng.integers(0, 40))}")
    if rng.random() < 0.3:
        # group config/state churn — must dirty the row via the device compare
        gi = int(rng.integers(0, G))
        groups.locked[gi] = bool(rng.random() < 0.5)
        groups.requested_nodes[gi] = int(rng.integers(0, 5))
        groups.scale_up_thr[gi] = int(rng.choice([60, 70, 80]))
    if rng.random() < 0.1:
        # group add/remove: the valid flip IS the add/remove at array level
        gi = int(rng.integers(0, G))
        groups.valid[gi] = not bool(groups.valid[gi])


@pytest.mark.parametrize("seed", [3, 17])
def test_multi_tick_parity_soak(seed):
    """After EVERY tick of a seeded churn sequence, the incremental decision
    (lazy or ordered, per the real gate) is bit-exact against a from-scratch
    ``decide_jit`` on the same resident cluster, and the maintained
    aggregates are bit-equal to ``compute_aggregates``."""
    G = 8
    rng, store, groups, cache = _store_world(seed, G)
    inc = IncrementalDecider(cache, refresh_every=0)  # audited manually below
    ordered_seen = light_seen = 0

    def one_tick(t):
        nonlocal ordered_seen, light_seen
        pod_dirty, node_dirty = store.drain_dirty()
        # groups re-uploaded every tick (they are tiny), exactly as the
        # backends do — config churn dirties rows via the device compare
        inc.apply_gathered(cache.gather_deltas(pod_dirty, node_dirty), groups)
        nv = store.as_pod_node_arrays()[1]
        tainted_any = bool(
            (np.asarray(nv.valid) & np.asarray(nv.tainted)).any())
        out, ordered = inc.decide(NOW, tainted_any)
        ref, ref_ordered = kernel.lazy_orders_decide(
            lambda w: jax.block_until_ready(kernel.decide_jit(
                cache.cluster, np.int64(NOW), with_orders=w)),
            tainted_any,
        )
        assert ordered == ref_ordered, f"tick {t}: protocol diverged"
        _assert_decisions_equal(out, ref, context=f"seed {seed} tick {t}")
        ordered_seen += ordered
        light_seen += not ordered
        # the maintained aggregates never drift (the refresh audit's claim,
        # checked every tick here rather than on a cadence)
        fresh = kernel.compute_aggregates_jit(cache.cluster)
        _assert_aggs_equal(inc.aggregates, fresh, context=f"tick {t}")

    # phase 1: adversarial random churn — drains, taints, deletes, group
    # add/remove; nearly every tick takes the ordered path
    for t in range(25):
        _random_churn(rng, store, groups, t, G)
        one_tick(t)
    # phase 2: drive the cluster to a CONVERGED steady state (balanced
    # round-robin load inside the (45, 70) band, every node untainted) so
    # the lazy LIGHT path — the delta_decide program — is exercised too
    store.upsert_nodes_batch(
        [f"n{i}" for i in range(40)], np.arange(40) % G,
        np.full(40, 4000), np.full(40, 16 * 10**9),
    )
    store.upsert_pods_batch(
        [f"p{i}" for i in range(180)], np.arange(180) % G,
        np.full(180, 500), np.full(180, 10**9),
    )
    groups.valid[:] = True
    groups.locked[:] = False
    for t in range(25, 30):
        # in-band churn: same-size re-upserts keep every group steady
        idx = (t * 7 + np.arange(7)) % 180
        store.upsert_pods_batch([f"p{i}" for i in idx], idx % G,
                                np.full(7, 500), np.full(7, 10**9))
        one_tick(t)
    # the sequence must have exercised BOTH protocol paths or the soak
    # proves less than it claims
    assert ordered_seen and light_seen, (ordered_seen, light_seen)
    assert inc.refresh() is True


def test_dirty_compaction_is_selective():
    """A tick that churns one group dirties (and re-decides) only the groups
    its lanes touched — the O(dirty) claim, observed via the mask."""
    rng, store, groups, cache = _store_world(seed=5)
    inc = IncrementalDecider(cache, refresh_every=0)
    store.drain_dirty()
    inc.decide(NOW, False)  # bootstrap full decide
    # churn three pods, all in group 2
    store.upsert_pods_batch(["p2", "p10", "p18"], np.full(3, 2),
                            np.full(3, 777), np.full(3, 10**9))
    pod_dirty, node_dirty = store.drain_dirty()
    inc.apply_gathered(cache.gather_deltas(pod_dirty, node_dirty))
    dirty = np.asarray(inc.aggregates.dirty)
    # the three pods' OLD groups plus their new group 2 — nothing else
    assert dirty[2]
    assert 0 < dirty.sum() <= 4
    out, ordered = inc.decide(NOW, False)
    # the light delta dispatch ran on exactly the dirty rows (a negative
    # delta may then re-dispatch ordered — the protocol's call, not ours)
    assert inc.last_dirty_count == int(dirty.sum())
    assert not np.asarray(inc.aggregates.dirty).any()
    ref, ref_ordered = kernel.lazy_orders_decide(
        lambda w: jax.block_until_ready(kernel.decide_jit(
            cache.cluster, np.int64(NOW), with_orders=w)), False)
    assert ordered == ref_ordered
    _assert_decisions_equal(out, ref)


def test_refresh_audit_detects_corruption():
    """The periodic refresh re-derives the aggregates and asserts
    bit-equality: corrupted maintained state raises (mode="raise") or is
    repaired with every group marked dirty (mode="repair")."""
    _, store, groups, cache = _store_world(seed=9)
    inc = IncrementalDecider(cache, refresh_every=0)
    assert inc.refresh() is True
    inc._aggs = dataclasses.replace(
        inc._aggs, cpu_req=inc._aggs.cpu_req + 1)  # simulate drift
    with pytest.raises(AggregateParityError, match="cpu_req"):
        inc.refresh()

    inc._on_mismatch = "repair"
    assert inc.refresh() is False
    assert np.asarray(inc.aggregates.dirty).all()
    # post-repair state is the recomputed truth
    assert inc.refresh() is True


def test_refresh_cadence_fires():
    _, store, groups, cache = _store_world(seed=13)
    inc = IncrementalDecider(cache, refresh_every=2)
    for _ in range(6):
        inc.decide(NOW, False)
    assert inc.refreshes == 3


def test_delta_decide_zero_dirty_tick():
    """A tick with nothing dirty still refreshes the [N] elementwise tail
    (reap ages against now) and stays bit-exact."""
    cluster = representative_cluster(seed=21)
    aggs = kernel.compute_aggregates_jit(cluster)
    light = kernel.decide_jit(cluster, np.int64(NOW), with_orders=False)
    prev = tuple(getattr(light, f) for f in kernel.GROUP_DECISION_FIELDS)
    idx = kernel.dirty_indices(np.zeros(6, bool))
    later = NOW + 10_000
    out, aggs2 = kernel.delta_decide_jit(cluster, aggs, prev, idx,
                                         np.int64(later))
    ref = kernel.decide_jit(cluster, np.int64(later), with_orders=False)
    _assert_decisions_equal(out, ref)


def _group_input(pods=11, nodes=2):
    from escalator_tpu.core import semantics as sem
    from escalator_tpu.testsupport.builders import (
        NodeOpts,
        PodOpts,
        build_test_nodes,
        build_test_pods,
    )

    cfg = sem.GroupConfig(
        min_nodes=0, max_nodes=100, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=70, slow_removal_rate=1,
        fast_removal_rate=2, soft_delete_grace_sec=300,
        hard_delete_grace_sec=900)
    return (build_test_pods(pods, PodOpts(cpu=[500], mem=[10**9])),
            build_test_nodes(nodes, NodeOpts(cpu=4000, mem=16 * 10**9)),
            cfg, sem.GroupState())


def test_incremental_backends_survive_group_pad_growth():
    """A 9th nodegroup grows pack_groups' power-of-two pad 8 -> 16 while the
    pod/node pads stand still: the [G]-shaped incremental state (aggregates,
    persistent columns) must REBUILD with it, not broadcast-crash against
    the resident shapes — on both incremental backends, both directions
    across the boundary, with decisions matching a fresh full-recompute
    backend."""
    from escalator_tpu.controller.backend import IncrementalJaxBackend, JaxBackend
    from escalator_tpu.controller.native_backend import NativeJaxBackend
    from escalator_tpu.k8s.cache import EventfulClient

    eights = [_group_input() for _ in range(8)]
    nines = eights + [_group_input()]

    backend = IncrementalJaxBackend(refresh_every=0)
    for group_inputs in (eights, nines, eights):
        got = backend.decide(group_inputs, now_sec=0)
        want = JaxBackend().decide(group_inputs, now_sec=0)
        assert [r.decision for r in got] == [w.decision for w in want]

    # native flavor: the store/bridge see only their configured filters (the
    # extra groups decide over empty lanes), but the [G] pack shape still
    # crosses the pad boundary and must rebuild the incremental state
    native = NativeJaxBackend(
        EventfulClient(nodes=[], pods=[]), [], incremental=True,
        refresh_every=0)
    for group_inputs in (eights, nines, eights):
        got = native.decide(group_inputs, now_sec=0)
        assert len(got) == len(group_inputs)


# ---------------------------------------------------------------------------
# Sharded variants
# ---------------------------------------------------------------------------


def test_grid_delta_decider_matches_per_block_kernel():
    """The grid's per-block delta decider is literally the kernel delta core
    per mesh row: bit-identical to the single-device light decide per block,
    zero collectives, dirty masks per shard."""
    from escalator_tpu.parallel import grid as gridlib

    mesh = gridlib.make_grid_mesh(num_group_shards=4)
    stacked = stacked_cluster(4, seed=7)
    Gb = stacked.groups.valid.shape[1]
    vaggs = jax.vmap(lambda c: kernel.compute_aggregates(c))(stacked)
    rng = np.random.default_rng(2)
    dirty = rng.random((4, Gb)) < 0.7
    vaggs = dataclasses.replace(vaggs, dirty=jnp.asarray(dirty))
    buckets = [kernel.dirty_indices(dirty[s]) for s in range(4)]
    D = max(b.shape[0] for b in buckets)
    idx = np.stack([
        np.pad(b, (0, D - b.shape[0]), constant_values=Gb) for b in buckets
    ])
    ref = jax.vmap(
        lambda c: kernel.decide(c, np.int64(NOW), with_orders=False)
    )(stacked)
    # stale persistent columns on the dirty rows: the delta scatter must
    # overwrite exactly those and keep the clean rows' values
    prev = tuple(
        jnp.where(jnp.asarray(dirty), jnp.zeros_like(getattr(ref, f)),
                  getattr(ref, f))
        if np.asarray(getattr(ref, f)).shape == dirty.shape
        else getattr(ref, f)
        for f in kernel.GROUP_DECISION_FIELDS
    )
    out, aggs2 = gridlib.make_grid_delta_decider(mesh)(
        stacked.groups, stacked.nodes, vaggs, prev, jnp.asarray(idx),
        np.int64(NOW))
    _assert_decisions_equal(out, ref, context="grid delta")
    assert not np.asarray(aggs2.dirty).any()


def _soa_take(soa, idx, oob, B):
    out = {}
    for f in soa.__dataclass_fields__:
        a = np.asarray(getattr(soa, f))
        v = np.zeros(B, a.dtype)
        sel = idx < oob
        v[sel] = a[idx[sel]]
        out[f] = v
    return type(soa)(**out)


def test_podaxis_delta_scatter_maintains_sharded_residency():
    """The pod-axis delta scatter updates the SHARDED resident cluster from
    a replicated (idx, old, new) batch with zero collectives, and the
    replicated aggregates stay bit-equal to a from-scratch recompute of the
    updated cluster; a node group move raises the exact-correction flag."""
    from escalator_tpu.parallel import mesh as meshlib, podaxis

    mesh = meshlib.make_mesh()
    cluster = podaxis.pad_pods_for_mesh(representative_cluster(seed=4), mesh)
    placed = podaxis.place(cluster, mesh)
    aggs = kernel.compute_aggregates_jit(placed)
    scat = podaxis.make_delta_scatter(mesh)
    P_ = cluster.pods.valid.shape[0]
    N_ = cluster.nodes.valid.shape[0]
    B = 8
    pidx = np.full(B, P_, np.int32)
    pidx[:5] = [0, 7, 33, 100, 161]        # lanes spread across shards
    pod_old = _soa_take(cluster.pods, pidx, P_, B)
    pn = {f: np.array(getattr(pod_old, f)) for f in pod_old.__dataclass_fields__}
    pn["cpu_milli"][:5] += 111
    pn["group"][1] = 2
    pn["valid"][2] = False                  # a delete
    pod_new = type(pod_old)(**pn)
    nidx = np.full(B, N_, np.int32)
    nidx[:2] = [3, 9]
    node_old = _soa_take(cluster.nodes, nidx, N_, B)
    nn = {f: np.array(getattr(node_old, f)) for f in node_old.__dataclass_fields__}
    nn["tainted"][0] = ~nn["tainted"][0]
    node_new = type(node_old)(**nn)
    out_cluster, aggs2, ng_changed = scat(
        placed.pods, placed.nodes, placed.groups, placed.groups,
        pidx, pod_old, pod_new, nidx, node_old, node_new, aggs)
    assert not bool(ng_changed)
    _assert_aggs_equal(aggs2, kernel.compute_aggregates_jit(out_cluster),
                       context="podaxis scatter")
    assert np.asarray(aggs2.dirty).any()
    # the resident pod columns took exactly the new values
    got_cpu = np.asarray(out_cluster.pods.cpu_milli)
    for b in range(5):
        assert got_cpu[pidx[b]] == pn["cpu_milli"][b]
    # delta decide on the sharded resident cluster: bit-exact vs full light.
    # (fresh aggregates: delta_decide_jit DONATES its aggs, and aggs2's
    # buffers are still needed by the second scatter below)
    G = cluster.groups.valid.shape[0]
    ref = kernel.decide_jit(out_cluster, np.int64(NOW), with_orders=False)
    prev = tuple(jnp.zeros_like(getattr(ref, f))
                 for f in kernel.GROUP_DECISION_FIELDS)
    alld = dataclasses.replace(kernel.compute_aggregates_jit(out_cluster),
                               dirty=jnp.ones(G, bool))
    out, _ = kernel.delta_decide_jit(
        out_cluster, alld, prev, kernel.dirty_indices(np.ones(G, bool)),
        np.int64(NOW))
    _assert_decisions_equal(out, ref, context="podaxis delta decide")

    # a node group move must raise the correction flag (pods outside the
    # batch change their pods-remaining contribution)
    node_old2 = _soa_take(out_cluster.nodes, nidx, N_, B)
    nn2 = {f: np.array(getattr(node_old2, f))
           for f in node_old2.__dataclass_fields__}
    nn2["group"][1] = (nn2["group"][1] + 1) % G
    out_cluster2, _, ng_changed2 = scat(
        out_cluster.pods, out_cluster.nodes, out_cluster.groups,
        out_cluster.groups, np.full(B, P_, np.int32), pod_new, pod_new,
        nidx, node_old2, type(node_old2)(**nn2), aggs2)
    assert bool(ng_changed2)


# ---------------------------------------------------------------------------
# Round 10: refresh-cadence parsing, background audit, incremental orders,
# host/device overlap
# ---------------------------------------------------------------------------

def test_parse_refresh_every():
    from escalator_tpu.ops.device_state import parse_refresh_every

    assert parse_refresh_every(8) == 8
    assert parse_refresh_every("8") == 8
    assert parse_refresh_every("  256 ") == 256
    assert parse_refresh_every("off") == 0
    assert parse_refresh_every(" OFF ") == 0
    for bad in ("0", "-3", "1.5", "abc", "", 0, -1, 2.5, True, None):
        with pytest.raises(ValueError, match="positive integer"):
            parse_refresh_every(bad)


def test_refresh_every_env_validation(monkeypatch):
    """The env spelling goes through the same validator: 0/negative/garbage
    fail LOUDLY at construction (the old int() accepted "0" as a silent
    disable), "off" is the documented disable."""
    _, store, groups, cache = _store_world(seed=23)
    monkeypatch.setenv("ESCALATOR_TPU_REFRESH_EVERY", "0")
    with pytest.raises(ValueError, match="ESCALATOR_TPU_REFRESH_EVERY"):
        IncrementalDecider(cache)
    monkeypatch.setenv("ESCALATOR_TPU_REFRESH_EVERY", "nope")
    with pytest.raises(ValueError, match="ESCALATOR_TPU_REFRESH_EVERY"):
        IncrementalDecider(cache)
    monkeypatch.setenv("ESCALATOR_TPU_REFRESH_EVERY", "off")
    assert IncrementalDecider(cache)._refresh_every == 0
    monkeypatch.setenv("ESCALATOR_TPU_REFRESH_EVERY", "7")
    assert IncrementalDecider(cache)._refresh_every == 7
    # programmatic: 0 stays the legacy disable, negatives reject
    assert IncrementalDecider(cache, refresh_every=0)._refresh_every == 0
    assert IncrementalDecider(cache, refresh_every="off")._refresh_every == 0
    with pytest.raises(ValueError, match="refresh_every"):
        IncrementalDecider(cache, refresh_every=-2)


@pytest.mark.parametrize("seed", [7])
def test_audit_lockstep_background_vs_sync(seed):
    """The ISSUE-5 equivalence proof: at every audited tick of a churn soak,
    the BACKGROUND audit's verdict (recompute + bit-compare against the
    frozen double-buffer snapshot, on a worker thread) equals the
    SYNCHRONOUS audit's verdict on the same tick's inputs — including one
    injected-drift tick where both must name the same mismatched columns."""
    G = 8
    rng, store, groups, cache = _store_world(seed, G)
    inc = IncrementalDecider(cache, refresh_every=0)  # cadence driven below
    for t in range(12):
        _random_churn(rng, store, groups, t, G)
        pd, nd = store.drain_dirty()
        inc.apply_gathered(cache.gather_deltas(pd, nd), groups)
        inc.decide(NOW, True)
        if t == 7:
            # inject drift so one lockstep point exercises the mismatch arm
            inc._aggs = dataclasses.replace(
                inc._aggs, mem_req=inc._aggs.mem_req + 1)
        # synchronous verdict on this tick's inputs (the reference)
        fresh = kernel.compute_aggregates_jit(cache.cluster)
        mm_sync = inc._mismatched_columns(inc._aggs, fresh)
        # background verdict on the SAME tick's inputs, adjudicated raw
        # (bypassing reconcile so the raise doesn't end the soak)
        inc._start_background_audit()
        fut = inc._audit_future
        inc._audit_future = None
        mm_bg = fut.result()
        assert mm_bg == mm_sync, f"tick {t}: {mm_bg} != {mm_sync}"
        assert (t == 7) == bool(mm_bg), f"tick {t}"
        if t == 7:
            assert "mem_req" in mm_bg
            inc._on_mismatch = "repair"
            inc._raise_or_repair(mm_bg)   # adopt truth, continue the soak
            inc._on_mismatch = "raise"


def test_background_audit_snapshot_is_frozen():
    """The double buffer's guarantee: mutations AFTER the snapshot — live
    aggregate drift, later-tick scatters — cannot change an in-flight
    audit's verdict. (No donation on the snapshot program; jaxlint pins
    that via the device_state.audit_snapshot entry.)"""
    _, store, groups, cache = _store_world(seed=31)
    inc = IncrementalDecider(cache, refresh_every=0)
    inc.decide(NOW, False)
    inc._start_background_audit()          # freezes a CLEAN state
    # corrupt the live aggregates and scatter a later tick while in flight
    inc._aggs = dataclasses.replace(inc._aggs, cpu_req=inc._aggs.cpu_req + 5)
    store.upsert_pods_batch(["p1"], [1], [999], [10**9])
    pd, nd = store.drain_dirty()
    inc.apply_gathered(cache.gather_deltas(pd, nd))
    assert inc.drain_audit() is True       # verdict is snapshot-time clean
    # whereas a synchronous audit of the LIVE state sees the drift
    with pytest.raises(AggregateParityError, match="cpu_req"):
        inc.refresh()


def test_background_audit_mismatch_raises_at_reconcile():
    """mode="raise" semantics survive the move off-path: the parity error
    surfaces at the next reconcile point (drain or next tick) with the
    mismatch counter bumped — not swallowed by the worker."""
    from escalator_tpu.metrics.metrics import registry

    def counter():
        v = registry.get_sample_value(
            "escalator_tpu_incremental_audit_mismatch_total")
        return 0.0 if v is None else v

    _, store, groups, cache = _store_world(seed=33)
    inc = IncrementalDecider(cache, refresh_every=2)  # background default on
    inc.decide(NOW, False)                 # tick 1
    inc._aggs = dataclasses.replace(
        inc._aggs, num_pods=inc._aggs.num_pods + 1)
    before = counter()
    inc.decide(NOW, False)                 # tick 2: audit starts, corrupted
    with pytest.raises(AggregateParityError, match="num_pods"):
        inc.drain_audit()
    assert counter() == before + 1
    assert inc.last_audit_ok is False


def test_background_audit_mismatch_repairs():
    """mode="repair" in background form: reconcile adopts a fresh recompute
    of the CURRENT resident cluster and marks every group dirty — after
    which decisions are bit-exact again."""
    _, store, groups, cache = _store_world(seed=35)
    inc = IncrementalDecider(cache, refresh_every=0, on_mismatch="repair")
    inc.decide(NOW, False)
    inc._aggs = dataclasses.replace(
        inc._aggs, num_nodes=inc._aggs.num_nodes + 1)
    inc._start_background_audit()
    assert inc.drain_audit() is False
    assert np.asarray(inc.aggregates.dirty).all()
    assert inc.refresh() is True           # repaired state IS the truth
    out, _ = inc.decide(NOW, False)
    ref, _ = kernel.lazy_orders_decide(
        lambda w: jax.block_until_ready(kernel.decide_jit(
            cache.cluster, np.int64(NOW), with_orders=w)), False)
    _assert_decisions_equal(out, ref, context="post-repair")


def _taint_tick(store, t):
    """Taint one node (fresh creation_ns: its sort keys move) — keeps every
    tick on the ordered path with a non-empty order-dirty set."""
    store.upsert_node(f"n{t % 40}", t % 8, 4000, 16 * 10**9,
                      creation_ns=10**9 + t, tainted=True,
                      taint_time_sec=NOW - 100)


@pytest.mark.parametrize("kwargs, forbidden, required", [
    ({}, (), ("bootstrap", "repair")),
    ({"order_repair_max_dirty_frac": -1.0}, ("repair",), ("full_sort",)),
    ({"incremental_orders": False}, ("repair", "bootstrap", "full_sort"), ()),
])
def test_ordered_incremental_paths_and_fallback(kwargs, forbidden, required):
    """Ordered ticks stay bit-exact on every order-state path: the repair
    merge (default), the forced full-sort fallback (threshold exceeded on
    every dirty tick), and the round-8 full ordered dispatch (opt-out).
    order_stats proves which path actually ran."""
    _, store, groups, cache = _store_world(seed=41)
    inc = IncrementalDecider(cache, refresh_every=0, **kwargs)
    inc.decide(NOW, False)                 # bootstrap decide: seeds prev_cols
    for t in range(4):
        _taint_tick(store, t)
        pd, nd = store.drain_dirty()
        inc.apply_gathered(cache.gather_deltas(pd, nd))
        out, ordered = inc.decide(NOW, True)
        assert ordered, f"tick {t} expected ordered"
        ref, _ = kernel.lazy_orders_decide(
            lambda w: jax.block_until_ready(kernel.decide_jit(
                cache.cluster, np.int64(NOW), with_orders=w)), True)
        _assert_decisions_equal(out, ref, context=f"tick {t} {kwargs}")
    for path in forbidden:
        assert path not in inc.order_stats, inc.order_stats
    for path in required:
        assert inc.order_stats.get(path, 0) >= 1, inc.order_stats


def test_overlap_mode_stays_bit_exact():
    """overlap=True changes only WHERE the tick blocks (ordered dispatches
    return unfenced; the caller's first device read absorbs the tail) —
    never the decision."""
    rng, store, groups, cache = _store_world(seed=47)
    inc = IncrementalDecider(cache, refresh_every=0, overlap=True)
    for t in range(8):
        _random_churn(rng, store, groups, t, 8)
        pd, nd = store.drain_dirty()
        inc.apply_gathered(cache.gather_deltas(pd, nd), groups)
        nv = store.as_pod_node_arrays()[1]
        tainted_any = bool(
            (np.asarray(nv.valid) & np.asarray(nv.tainted)).any())
        out, ordered = inc.decide(NOW, tainted_any)
        ref, ref_ordered = kernel.lazy_orders_decide(
            lambda w: jax.block_until_ready(kernel.decide_jit(
                cache.cluster, np.int64(NOW), with_orders=w)), tainted_any)
        assert ordered == ref_ordered
        _assert_decisions_equal(out, ref, context=f"overlap tick {t}")
