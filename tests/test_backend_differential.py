"""Randomized multi-tick differential: every compute backend vs golden.

test_kernel_parity locks decide-level parity on random clusters; this locks
the CONTROLLER-level trajectory — provider target sizes, which nodes end up
tainted (compared by creation-order ordinal, not name: the test builders
name nodes from a module-global counter, so names differ between two
separately-built worlds even when semantics agree), and the surviving node
count — over multi-tick lifecycles on randomized worlds whose pod load
rises then collapses, so scale-up, cloud fill, taint selection and the
grace-period reaper all actually fire. The executors consume the kernel's
ordering windows and grace timestamps, so a divergence here catches
consumer-side bugs the decide-level tests cannot (wrong window slicing,
off-by-one in offsets, timestamp plumbing).

Identical semantics across backends is the framework's core contract
(docs/best-practices.md); golden is the oracle.
"""

import numpy as np
import pytest

from escalator_tpu.controller.backend import GoldenBackend
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)
from tests.test_controller import (
    BACKENDS,
    LABEL_KEY,
    LABEL_VALUE,
    World,
    make_opts,
)

SEEDS = [11, 47, 203]
TICKS = 8

#: node shape per seed (node cpu/mem must be identical across the compared
#: worlds AND known to the cloud-fill step)
_NODE_CPU, _NODE_MEM = 4000, 16 * 10**9


def _random_world(seed, backend):
    rng = np.random.default_rng(seed)
    nodes = build_test_nodes(int(rng.integers(2, 6)), NodeOpts(
        cpu=_NODE_CPU, mem=_NODE_MEM))
    opts = make_opts(
        min_nodes=int(rng.integers(0, 2)),
        taint_lower_capacity_threshold_percent=int(rng.integers(15, 35)),
        taint_upper_capacity_threshold_percent=int(rng.integers(36, 60)),
        scale_up_threshold_percent=int(rng.integers(61, 85)),
        fast_node_removal_rate=int(rng.integers(1, 4)),
        soft_delete_grace_period="2m",
        hard_delete_grace_period="4m",
    )
    return World(opts, nodes=nodes, pods=[], backend=backend)


def _trajectory(seed, backend_factory, ticks=TICKS):
    """Per-tick (provider target, tainted-node ordinals, node count).

    Tainted nodes are identified by their index in the client's node list
    (creation order — deterministic per seed), which is stable across the
    two worlds being compared even though absolute node NAMES are not.
    """
    w = _random_world(seed, backend_factory())
    rng = np.random.default_rng(seed + 999)  # same churn stream per backend
    traj = []
    for t in range(ticks):
        # load profile: ramp up hard for the first half (drives scale-up),
        # then collapse (drives taint + reap through the short grace)
        if t < ticks // 2:
            for _ in range(int(rng.integers(8, 20))):
                w.client.add_pod(build_test_pods(1, PodOpts(
                    cpu=[int(rng.choice([250, 500, 1500]))], mem=[10**9],
                    node_selector_key=LABEL_KEY,
                    node_selector_value=LABEL_VALUE))[0])
        else:
            pods = w.client.list_pods()
            for p in pods[: int(len(pods) * 0.7)]:
                w.client.remove_pod(p)
        # the cloud "delivers" whatever the provider was asked for, so
        # over-provisioning after the collapse is real and taintable
        w.simulate_cloud_fills_nodes(_NODE_CPU, _NODE_MEM)
        w.clock.advance(int(rng.integers(130, 400)))
        w.tick()
        node_names = [n.name for n in w.client.list_nodes()]
        tainted = sorted(
            node_names.index(n.name) for n in w.tainted_nodes())
        traj.append((w.group.target_size(), tainted, len(node_names)))
    return traj


_golden_cache = {}


def _golden(seed):
    if seed not in _golden_cache:
        _golden_cache[seed] = _trajectory(seed, lambda: GoldenBackend())
    return _golden_cache[seed]


def test_scenarios_are_not_vacuous():
    """The seeds must actually drive the dimensions this test locks: at
    least one golden trajectory with a non-empty taint set and at least one
    with a node-count decrease (a reap). Guards against the scenario
    generator silently degenerating into a pure scale-up test."""
    trajs = [_golden(s) for s in SEEDS]
    assert any(t for traj in trajs for (_, t, _) in traj), (
        "no seed ever tainted a node", trajs)
    assert any(
        traj[i + 1][2] < traj[i][2]
        for traj in trajs for i in range(len(traj) - 1)
    ), ("no seed ever reaped a node", trajs)


@pytest.mark.parametrize(
    "backend_kind", [k for k in BACKENDS if k != "golden"])
@pytest.mark.parametrize("seed", SEEDS)
def test_backend_trajectory_matches_golden(backend_kind, seed):
    want = _golden(seed)
    got = _trajectory(seed, BACKENDS[backend_kind])
    assert got == want, (
        f"{backend_kind} diverged from golden on seed {seed}:\n"
        f"golden: {want}\n{backend_kind}: {got}"
    )
