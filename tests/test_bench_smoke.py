"""Smoke coverage for the benchmark harness itself.

bench.py is the artifact the driver runs at round end; a regression that
crashes it silently costs the round's headline. These tests drive its
helpers at tiny scale on CPU (the full configs are the TPU campaign's job,
tools/tpu_campaign.sh) so breakage is caught in CI, not at capture time.
"""

from __future__ import annotations

import importlib.util
import json
import pathlib
import sys

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("bench", mod)
    spec.loader.exec_module(mod)
    return mod


def test_cluster_synthesis_invariants(bench):
    rng = np.random.default_rng(3)
    c = bench._rng_cluster_arrays(rng, 4, 200, 50, mixed=True,
                                  heterogeneous=True, tainted_frac=0.3,
                                  cordoned_frac=0.1)
    assert c.pods.group.shape == (200,) and c.nodes.group.shape == (50,)
    # group-contiguous layout (the Pallas windowed path's precondition)
    assert (np.diff(c.pods.group) >= 0).all()
    assert (np.diff(c.nodes.group) >= 0).all()
    assert c.pods.cpu_milli.dtype == np.int64
    # tainted and cordoned are disjoint by construction
    assert not (c.nodes.tainted & c.nodes.cordoned).any()


def test_time_decide_tiny(bench):
    import jax

    from escalator_tpu.ops import kernel as _k  # noqa: F401 registers pytrees

    rng = np.random.default_rng(4)
    cluster = jax.device_put(bench._rng_cluster_arrays(rng, 2, 64, 16))
    med, mn = bench._time_decide_med_min(cluster, np.int64(0), iters=2)
    assert 0 < mn <= med
    assert bench._time_decide(cluster, np.int64(0), iters=2) > 0


def test_fused_tick_tiny(bench):
    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import NativeStateStore
    from escalator_tpu.ops.device_state import DeviceClusterCache

    rng = np.random.default_rng(5)
    store = NativeStateStore(pod_capacity=1 << 10, node_capacity=1 << 8)
    store.upsert_pods_batch([f"p{i}" for i in range(300)],
                            rng.integers(0, 4, 300),
                            np.full(300, 500), np.full(300, 10**9))
    store.upsert_nodes_batch([f"n{i}" for i in range(60)],
                             rng.integers(0, 4, 60),
                             np.full(60, 4000), np.full(60, 16 * 10**9))
    pods_v, nodes_v = store.as_pod_node_arrays()
    base = bench._rng_cluster_arrays(rng, 4, 1, 1)
    store.drain_dirty()
    cache = DeviceClusterCache(
        ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v))
    ms = bench._time_fused_tick(store, cache, "xla", rng, np.int64(0),
                                n_churn=32, iters=2)
    assert ms > 0
    # the shared tick-phase protocol, both transfer layouts (cfg6/cfg13 use
    # this; packed=True is the two-byte-buffer variant priced per capture)
    for packed in (False, True):
        phases = bench._native_tick_phases(
            store, cache, "xla", rng, np.int64(0), num_pods=300,
            num_groups=4, n_churn=32, iters=2, packed=packed)
        assert phases["total"] > 0
        # round 13: every e2e row carries its tail columns too
        assert set(phases) == {"upsert", "drain", "scatter", "decide",
                               "total", "total_p99", "total_p999"}
        assert phases["total_p999"] >= phases["total_p99"] >= phases["total"]


def test_observability_overhead_and_recorder_summary_tiny(bench):
    """The cfg14 observability-overhead row helper and the recorder phase
    summarizer at tiny scale: enabled/disabled arms both measured, overhead
    clamped non-negative, and the summarizer medians the right root."""
    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import NativeStateStore
    from escalator_tpu.observability import spans
    from escalator_tpu.ops.device_state import DeviceClusterCache, IncrementalDecider

    rng = np.random.default_rng(8)
    store = NativeStateStore(pod_capacity=1 << 9, node_capacity=1 << 7)
    store.upsert_pods_batch([f"p{i}" for i in range(100)],
                            np.arange(100) % 4,
                            np.full(100, 500), np.full(100, 10**9))
    store.upsert_nodes_batch([f"n{i}" for i in range(20)],
                             np.arange(20) % 4,
                             np.full(20, 4000), np.full(20, 16 * 10**9))
    pods_v, nodes_v = store.as_pod_node_arrays()
    base = bench._rng_cluster_arrays(rng, 4, 1, 1)
    store.drain_dirty()
    cache = DeviceClusterCache(
        ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v))
    inc = IncrementalDecider(cache, refresh_every=0)
    inc.decide(np.int64(0), False)
    row = bench._observability_overhead(
        store, cache, inc, np.int64(0), 100, 4, 500, iters=3, n_churn=8)
    assert row["enabled_ms"] > 0 and row["disabled_ms"] > 0
    assert row["overhead_ms"] >= 0 and row["overhead_pct"] is not None
    assert spans.enabled()   # the helper must re-enable recording
    # recorder summary keyed by root name, per-phase tail stats in ms
    with spans.span("tiny_root"):
        inc.decide(np.int64(0), False)
    summary = bench._recorder_phase_stats("tiny_root")
    assert summary["_ticks"] >= 1
    assert "delta_decide" in summary
    stats = summary["delta_decide"]
    assert {"p50", "p99", "p999", "min"} <= set(stats)
    assert stats["min"] <= stats["p50"] <= stats["p99"] <= stats["p999"]


def test_plugin_roundtrip_tiny(bench):
    rng = np.random.default_rng(6)
    host = bench._rng_cluster_arrays(rng, 2, 100, 20)
    out = bench._bench_plugin_roundtrip(host, np.int64(0))
    assert out["cfg12_plugin_roundtrip_2048g_100kpods_ms"] > 0
    assert out["cfg12_plugin_roundtrip_min_ms"] <= (
        out["cfg12_plugin_roundtrip_2048g_100kpods_ms"])


def test_capture_summary_reads_repo_artifacts(bench):
    rows = bench._summarize_tpu_captures()
    by_file = {r["file"]: r for r in rows}
    # every committed, fully-written campaign capture must summarize cleanly
    # (an in-flight capture is empty and emits no row at all — skip those).
    # Captures live under tpu_traces/ since round 15; the root glob stays
    # for strays from an older campaign script.
    committed = sorted(
        list(REPO.glob("TPU_BENCH_2026*.json"))
        + list((REPO / "tpu_traces").glob("TPU_BENCH_2026*.json")))
    assert committed, "no campaign captures found under tpu_traces/"
    for path in committed:
        if not path.stat().st_size:
            continue
        assert path.name in by_file, f"{path.name} missing from tpu_captures"
        assert "error" not in by_file[path.name], by_file[path.name]
        assert by_file[path.name]["value_ms"] > 0
    # prior-round driver benches ride along flagged
    assert any(r.get("prior_round") for r in rows)


def test_capture_summary_surfaces_dead_capture(bench, tmp_path, monkeypatch):
    # point the summarizer's glob at a temp dir rather than writing fixture
    # files into the real repo root (a hard-killed run would strand them in
    # every later bench artifact's tpu_captures)
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    dead = tmp_path / "TPU_BENCH_19700101T000000Z.json"
    dead.write_text(json.dumps({"note": "died mid-run"}) + "\n")
    rows = bench._summarize_tpu_captures()
    row = next(r for r in rows if r["file"] == dead.name)
    assert row["error"] == "no bench record in capture"


def test_partial_flush_and_salvage_summary(bench, tmp_path, monkeypatch):
    """The mid-run partial artifact (wedge salvage): _flush_partial writes
    atomically to the per-run path, accumulates sections across calls, and
    _summarize_tpu_partials reports a salvaged file's completed sections —
    the contract tools/tpu_campaign.sh's stall watchdog relies on."""
    partial = tmp_path / "TPU_PARTIAL_19700101T000000Z.json"
    monkeypatch.setattr(bench, "_PARTIAL_PATH", str(partial))
    detail = {"host_load_avg_start": [0.1], "cfg1_1ng_500pods_ms": 0.123456}
    bench._flush_partial(detail, "FakeDev", degraded=True)
    got = json.loads(partial.read_text())
    assert got["partial"] is True
    assert "CPU fallback" in got["device"]
    assert got["detail"]["cfg1_1ng_500pods_ms"] == 0.123  # rounded like main()
    # later flushes supersede in place (atomic replace, no .tmp left behind)
    detail["cfg6_native_tick_1pct_churn_ms"] = 1.5
    detail["cfg13_native_tick_1Mpods_1pct_churn_ms"] = 2.0
    detail["cfg9_pallas_error"] = "lowering failed"   # NOT a completed section
    detail["cfg12_skipped"] = "grpc unavailable"      # NOT a completed section
    # a wedge mid-matrix leaves only the in-progress key: NOT a completed
    # section either (ADVICE r5 — the final key is written only at the end)
    detail["cfg10_ffd_pack_partial"] = {"rows": {}}
    bench._flush_partial(detail, "FakeDev", degraded=True)
    got = json.loads(partial.read_text())
    assert got["detail"]["cfg6_native_tick_1pct_churn_ms"] == 1.5
    assert not (tmp_path / (partial.name + ".tmp")).exists()
    # the salvage summary picks it up, names its MEASURED sections in numeric
    # order (error/skip markers excluded — a failed section is not salvaged
    # evidence), and never lets a partial masquerade as a full capture
    # (different glob prefix)
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    rows = bench._summarize_tpu_partials()
    row = next(r for r in rows if r["file"] == partial.name)
    assert row["sections"] == ["cfg1", "cfg6", "cfg13"]
    assert row["degraded"] is True
    assert row["e2e_tick_1pct_ms"] == 1.5
    assert not any(r["file"].startswith("TPU_PARTIAL")
                   for r in bench._summarize_tpu_captures()
                   if "file" in r)


def test_smoke_mode_parity(bench, tmp_path, monkeypatch):
    """`python bench.py --smoke` (tier-1-safe): the round-6 hot paths — the
    group-block-sharded ordering tail and both blocked-FFD scan programs —
    run at tiny shapes with parity asserted inside run_smoke itself."""
    # keep the smoke flight dump + replay report out of the repo root
    monkeypatch.setenv("ESCALATOR_TPU_FLIGHT_DUMP",
                       str(tmp_path / "flight-smoke.json"))
    monkeypatch.setenv("ESCALATOR_TPU_REPLAY_SMOKE",
                       str(tmp_path / "replay-smoke.json"))
    monkeypatch.setenv("ESCALATOR_TPU_HOST_PHASES_SMOKE",
                       str(tmp_path / "host-phases.json"))
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_SMOKE",
                       str(tmp_path / "tail-smoke.json"))
    monkeypatch.setenv("ESCALATOR_TPU_TRACE_SMOKE",
                       str(tmp_path / "smoke.trace.json"))
    monkeypatch.setenv("ESCALATOR_TPU_FLEET_SMOKE",
                       str(tmp_path / "fleet-smoke.json"))
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_SMOKE",
                       str(tmp_path / "memory-smoke.json"))
    monkeypatch.setenv("ESCALATOR_TPU_JOURNEY_SMOKE",
                       str(tmp_path / "journey-smoke.json"))
    monkeypatch.setenv("ESCALATOR_TPU_PROVENANCE_SMOKE",
                       str(tmp_path / "provenance-smoke.json"))
    out = bench.run_smoke()
    assert out["smoke_cfg8_parity"] == "ok"
    assert out["smoke_cfg10_parity"] == "ok"
    # the prepass exercised BOTH scan programs, not one of them twice
    assert out["smoke_cfg10_replicaset_path"] == "runs"
    assert out["smoke_cfg10_mixed_path"] == "pods"
    # round 8: the incremental/full decide contract (delta_decide on dirty
    # rows bit-exact vs full recompute, both lazy paths) is tier-1-locked
    assert out["smoke_cfg14_parity"] == "ok"
    assert any(c > 0 for c in out["smoke_cfg14_dirty_counts"])
    # round 9: the flight recorder saw the smoke ticks (run_smoke asserts
    # the phase names + fencing + overhead bound internally; here we lock
    # the artifact surface CI uploads)
    assert out["smoke_flight_recorder_depth"] > 0
    assert out["smoke_observability_overhead_ms"] < 0.75
    # round 11: the replay smoke re-executed a dumped ring through the real
    # debug-replay verb to identical per-tick digests, and wrote the report
    # artifact CI uploads
    assert out["smoke_replay"] == "ok"
    replay_report = json.loads(
        (tmp_path / "replay-smoke.json").read_text())
    assert replay_report["ok"] and replay_report["replayed"] == 4
    dump = json.loads((tmp_path / "flight-smoke.json").read_text())
    assert dump["flight_recorder"] is True and dump["reason"] == "smoke"
    assert dump["ticks"], "smoke dump carries no tick records"
    assert any(p["name"] == "delta_decide"
               for t in dump["ticks"] for p in t["phases"])
    # round 12: streaming ingestion smoke — event-driven vs re-list digest
    # parity on every exercised store kind, the production phase taxonomy
    # (event_drain / triple_build, run_smoke asserts the names internally),
    # and the host-phase breakdown artifact CI uploads
    for kind in out["smoke_streaming_store_kinds"]:
        assert out[f"smoke_streaming_parity_{kind}"] == "ok"
    assert "numpy" in out["smoke_streaming_store_kinds"]
    assert out["smoke_streaming_phases"] == "ok"
    assert out["smoke_streaming_backend_store"] in ("native", "numpy")
    host_phases = json.loads((tmp_path / "host-phases.json").read_text())
    assert "event_drain" in host_phases["native_backend_tick_ms"]
    assert "triple_build" in host_phases["native_backend_tick_ms"]
    for kind in out["smoke_streaming_store_kinds"]:
        assert host_phases["streaming_ticks_ms"][kind]["_ticks"] >= 1
    dump_phase_names = {p["name"]
                        for t in dump["ticks"] for p in t["phases"]}
    assert {"event_drain", "triple_build"} <= dump_phase_names
    # round 13: the tail-latency loop — histogram accuracy vs np.percentile,
    # the tail-capture fire path (reason="tail" dump + rate limit), and the
    # debug-trace round-trip producing a merged client+server Perfetto
    # trace — all asserted inside run_smoke; here we lock the artifact
    # surface CI uploads
    assert out["smoke_tail_quantile_accuracy"] == "ok"
    assert out["smoke_tail_capture"] == "ok"
    assert out["smoke_trace_export"] == "ok"
    tail_report = json.loads((tmp_path / "tail-smoke.json").read_text())
    assert tail_report["tail_capture"]["duration_ms"] > (
        tail_report["tail_capture"]["threshold_ms"])
    assert set(tail_report["quantile_accuracy"]) == {
        "bimodal", "heavy_tail", "single_sample"}
    trace = json.loads((tmp_path / "smoke.trace.json").read_text())
    slices = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert slices and any(e["args"].get("remote") for e in slices)
    # round 14: the fleet loop — C=8 tenants through the real gRPC fleet
    # server (run_smoke asserts coalescing, per-tenant digest parity and
    # the backpressure path internally; here we lock the artifact surface)
    assert out["smoke_fleet_mode"] == "grpc"
    assert out["smoke_fleet_parity"] == "ok"
    assert out["smoke_fleet_backpressure"] == "ok"
    assert out["smoke_fleet_max_batch"] >= 2
    fleet_report = json.loads((tmp_path / "fleet-smoke.json").read_text())
    assert fleet_report["tenants"] == 8
    assert fleet_report["backpressure"]["rejected"] == 2
    assert all(v > 0 for v in fleet_report["backpressure"]["retry_after_ms"])
    # round 15: the device resource observatory — per-owner budgets
    # asserted, the forced leak fired the memory watchdog, the compile
    # ring attributed, and debug-profile round-tripped a real capture
    # through the plugin RPC (run_smoke asserts the details internally;
    # here we lock the artifact surface CI uploads)
    assert out["smoke_resource_budgets"] == "ok"
    assert out["smoke_memory_watchdog"] == "ok"
    assert out["smoke_compile_attribution"] == "ok"
    assert out["smoke_profile_rpc"] == "ok"
    memory_report = json.loads((tmp_path / "memory-smoke.json").read_text())
    for need in ("cluster_arrays", "group_aggregates", "decision_columns"):
        row = memory_report["owners"][need]
        assert row["nbytes"] == row["budget_bytes"] > 0, (need, row)
    assert memory_report["forced_leak"]["growth_bytes"] > 0
    assert any(f.endswith(".xplane.pb")
               for f in memory_report["profile_rpc"]["files"])
    # round 19: the decision provenance leg — explain-vs-columns bit
    # parity over the real Explain RPC, a forced oscillation firing the
    # flap watchdog (journal + reason="flap" dump, steady tenant silent),
    # and the debug-explain CLI round-trip (run_smoke asserts the details
    # internally; here we lock the artifact surface CI uploads)
    assert out["smoke_provenance_mode"] == "grpc"
    assert out["smoke_provenance_flap"] == "ok"
    assert out["smoke_provenance_parity"] == "ok"
    assert out["smoke_provenance_cli"] == "ok"
    prov_text = (tmp_path / "provenance-smoke.json").read_text()
    prov_report = json.loads(prov_text)
    assert prov_report["flaps"]["fired"] >= 1
    assert prov_report["flaps"]["dump_reason"] == "flap"
    assert prov_report["flaps"]["dump_groups"], prov_report["flaps"]
    assert prov_report["explain"]["mismatches"] == 0
    assert set(prov_report["explain"]["threshold_branches"]) <= {
        "scale_down_fast", "scale_down_slow", "scale_up", "hold"}
    assert prov_report["cli"] == {"discovery_rc": 0, "tenant_rc": 0}
    # smoke artifacts are canonical: sorted keys + fixed float precision,
    # so a canonical re-dump is byte-identical (round 19 satellite)
    assert prov_text == json.dumps(
        bench._canon_smoke(prov_report), indent=1, sort_keys=True) + "\n"
    # per-leg duration table (round 15 satellite): every major leg is
    # named in both the stdout dict and the persisted artifact
    legs = out["smoke_leg_seconds"]
    assert {"cfg8_order_tail", "cfg10_ffd", "cfg14_incremental", "replay",
            "streaming", "recorder_overhead", "tail_trace", "fleet",
            "resources", "provenance"} <= set(legs)
    assert all(sec >= 0 for sec in legs.values())
    assert memory_report["leg_seconds"] == legs


def test_archived_e2e_filter(bench):
    rows = [
        {"file": "a", "value_ms": 1.4, "headline_scope": "end_to_end_x"},
        {"file": "b", "value_ms": 9.9, "headline_scope": "end_to_end_x",
         "degraded": True},
        {"file": "c", "value_ms": 0.2, "headline_scope": "(pre-r4 kernel-only)"},
        {"file": "d", "value_ms": 5.0, "headline_scope": "end_to_end_x",
         "prior_round": True},
        {"file": "e", "error": "no bench record in capture"},
        {"file": "f", "value_ms": 2.0, "headline_scope": "end_to_end_y"},
    ]
    rows.append({"file": "g", "value_ms": None,  # record written, value lost
                 "headline_scope": "end_to_end_x"})
    assert bench._archived_e2e_values(rows) == [1.4, 2.0]
    # and against the real repo artifacts: structural only (artifact counts
    # and values churn every capture round)
    live = bench._archived_e2e_values(bench._summarize_tpu_captures())
    assert all(isinstance(v, float) and v > 0 for v in live)
