"""gRPC compute plugin: codec round-trip, service end-to-end on a local socket,
controller running over GrpcBackend, and the CPU fallback path."""

import random

import numpy as np
import pytest

from escalator_tpu.core import semantics as sem
from escalator_tpu.core.arrays import pack_cluster
from escalator_tpu.ops import kernel
from escalator_tpu.plugin import codec
from escalator_tpu.plugin.client import ComputeClient, GrpcBackend
from escalator_tpu.plugin.server import make_server

from tests.test_kernel_parity import NOW, random_group


@pytest.fixture(scope="module")
def plugin():
    server = make_server("127.0.0.1:0")
    port = server._escalator_bound_port
    server.start()
    client = ComputeClient(f"127.0.0.1:{port}")
    yield client
    client.close()
    server.stop(grace=None)


def test_codec_round_trip():
    rng = random.Random(1)
    groups = [random_group(rng, gi) for gi in range(6)]
    cluster = pack_cluster(groups, pad_pods=256, pad_nodes=128, pad_groups=8)
    frame = codec.encode_cluster(cluster, NOW)
    decoded, now = codec.decode_cluster(frame)
    assert now == NOW
    for section in ("groups", "pods", "nodes"):
        a, b = getattr(cluster, section), getattr(decoded, section)
        for f in a.__dataclass_fields__:
            np.testing.assert_array_equal(getattr(a, f), getattr(b, f), err_msg=f)


def test_codec_rejects_garbage():
    with pytest.raises(ValueError, match="bad magic"):
        codec.decode_cluster(b"NOPE" + b"\0" * 64)


def test_codec_tolerates_pre_emptiest_frames():
    """A frame from a peer that predates the g.emptiest column must decode with
    the documented default (no group uses emptiest-first), not KeyError —
    mixed-version interop is explicit, not accidental."""
    rng = random.Random(2)
    groups = [random_group(rng, gi) for gi in range(4)]
    cluster = pack_cluster(groups, pad_pods=128, pad_nodes=64, pad_groups=8)
    named = [("__now__", np.array([NOW], np.int64))]
    for prefix, section in (
        ("g.", cluster.groups), ("p.", cluster.pods), ("n.", cluster.nodes)
    ):
        for f in section.__dataclass_fields__:
            if prefix + f == "g.emptiest":
                continue  # the old peer never heard of it
            named.append((prefix + f, getattr(section, f)))
    old_frame = codec._encode_arrays(named)
    decoded, now = codec.decode_cluster(old_frame)
    assert now == NOW
    assert decoded.groups.emptiest.dtype == np.bool_
    assert not decoded.groups.emptiest.any()
    np.testing.assert_array_equal(decoded.groups.valid, cluster.groups.valid)


def test_codec_missing_required_field_is_named_error():
    with pytest.raises(ValueError, match="p.cpu_milli"):
        named = [("__now__", np.array([NOW], np.int64))]
        rng = random.Random(3)
        cluster = pack_cluster(
            [random_group(rng, 0)], pad_pods=64, pad_nodes=32, pad_groups=8
        )
        for prefix, section in (
            ("g.", cluster.groups), ("p.", cluster.pods), ("n.", cluster.nodes)
        ):
            for f in section.__dataclass_fields__:
                if prefix + f == "p.cpu_milli":
                    continue
                named.append((prefix + f, getattr(section, f)))
        codec.decode_cluster(codec._encode_arrays(named))


def test_codec_round_trip_at_scale():
    """100k-pod frame: the marshalling hard part (SURVEY §7) across the plugin
    boundary — every column exact through the single-copy encoder."""
    import bench as benchmod

    nprng = np.random.default_rng(0)
    cluster = benchmod._rng_cluster_arrays(nprng, 512, 100_000, 20_000,
                                           mixed=True, heterogeneous=True,
                                           tainted_frac=0.1)
    frame = codec.encode_cluster(cluster, NOW)
    decoded, now = codec.decode_cluster(frame)
    assert now == NOW
    for section in ("groups", "pods", "nodes"):
        a, b = getattr(cluster, section), getattr(decoded, section)
        for f in a.__dataclass_fields__:
            np.testing.assert_array_equal(getattr(a, f), getattr(b, f), err_msg=f)


def test_health(plugin):
    h = plugin.health()
    assert h["ok"] is True
    assert "device" in h and "version" in h
    # stale-but-alive detection surface: decide-traffic age + recorder depth
    assert "last_decide_age_sec" in h and "flight_recorder_depth" in h
    assert "ticks_served" in h


def test_health_last_decide_age_tracks_traffic(plugin):
    rng = random.Random(21)
    before = plugin.health()
    cluster = pack_cluster([random_group(rng, 0)],
                           pad_pods=64, pad_nodes=32, pad_groups=8)
    plugin.decide_arrays(cluster, NOW)
    after = plugin.health()
    assert after["ticks_served"] == before["ticks_served"] + 1
    # fresh decide -> small age; -1 only before the first decide ever
    assert 0 <= after["last_decide_age_sec"] < 60
    assert after["flight_recorder_depth"] >= 1


def test_plugin_dump_returns_server_flight_record(plugin):
    rng = random.Random(22)
    cluster = pack_cluster([random_group(rng, 1)],
                           pad_pods=64, pad_nodes=32, pad_groups=8)
    plugin.decide_arrays(cluster, NOW)
    doc = plugin.dump()
    assert doc["flight_recorder"] is True and doc["reason"] == "plugin-dump"
    assert doc["depth"] >= 1
    server_ticks = [t for t in doc["ticks"] if t["root"] == "plugin_decide"]
    assert server_ticks, [t["root"] for t in doc["ticks"]]
    names = {p["name"] for p in server_ticks[-1]["phases"]}
    assert {"decode", "decide", "encode"} <= names


def test_debug_dump_cli_fetches_plugin_ring(plugin, tmp_path, capsys):
    """``escalator-tpu debug-dump`` pulls the plugin's flight record over
    the Dump RPC — to a file, and to stdout with --output -."""
    from escalator_tpu.cli import main as cli_main
    import json

    rng = random.Random(24)
    cluster = pack_cluster([random_group(rng, 2)],
                           pad_pods=64, pad_nodes=32, pad_groups=8)
    plugin.decide_arrays(cluster, NOW)
    out_file = tmp_path / "flight.json"
    rc = cli_main(["debug-dump", "--plugin-address", plugin.address,
                   "--output", str(out_file)])
    assert rc == 0
    doc = json.loads(out_file.read_text())
    assert doc["flight_recorder"] is True and doc["depth"] >= 1
    capsys.readouterr()
    rc = cli_main(["debug-dump", "--plugin-address", plugin.address,
                   "--output", "-"])
    assert rc == 0
    stdout_doc = json.loads(capsys.readouterr().out)
    assert stdout_doc["reason"] == "plugin-dump"


def test_remote_decide_nests_server_phases_under_caller_tick(plugin):
    """The cross-boundary contract: a plugin-routed decide grafts the
    server-side phases under the caller's span context, so ONE flight
    record reads end-to-end across the process boundary."""
    from escalator_tpu import observability as obs

    rng = random.Random(23)
    groups = [random_group(rng, gi) for gi in range(3)]
    cluster = pack_cluster(groups, pad_pods=256, pad_nodes=128, pad_groups=8)
    with obs.span("caller_tick"):
        with obs.span("rpc", kind="rpc"):
            out, server_phases = plugin.decide_arrays_traced(
                cluster, NOW, span_ctx={"path": obs.current_path()})
        obs.graft(server_phases, under="caller_tick/rpc")
    assert server_phases, "server shipped no span timeline"
    rec = obs.RECORDER.last()
    assert rec["root"] == "caller_tick"
    paths = {p["path"] for p in rec["phases"]}
    assert "caller_tick/rpc/plugin_decide/decide" in paths, sorted(paths)
    assert "caller_tick/rpc/plugin_decide/decode" in paths
    # the server-side record carries the caller's span context (in-process
    # server here, so the shared RECORDER holds both sides)
    server_rec = next(r for r in reversed(obs.RECORDER.snapshot())
                      if r["root"] == "plugin_decide")
    assert server_rec.get("caller") == "caller_tick/rpc"
    # decide phase is device-fenced on the server
    decide = next(p for p in server_rec["phases"] if p["name"] == "decide")
    assert decide["fenced"] is True


def test_controller_over_grpc_records_nested_tick():
    """A full controller tick over GrpcBackend produces one timeline with
    controller, client and (grafted) server phases."""
    from escalator_tpu import observability as obs
    from tests.test_controller import World, make_opts
    from escalator_tpu.testsupport.builders import (
        NodeOpts, PodOpts, build_test_nodes, build_test_pods,
    )

    server = make_server("127.0.0.1:0")
    server.start()
    try:
        backend = GrpcBackend(f"127.0.0.1:{server._escalator_bound_port}")
        pods = build_test_pods(10, PodOpts(
            cpu=[500], mem=[10**9],
            node_selector_key="customer", node_selector_value="buildeng"))
        nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
        w = World(make_opts(), nodes=nodes, pods=pods, backend=backend)
        w.tick()
        rec = obs.RECORDER.last()
        assert rec["root"] == "tick" and rec["backend"] == "grpc"
        paths = {p["path"] for p in rec["phases"]}
        assert "tick/decide/grpc/rpc/plugin_decide/decide" in paths, sorted(paths)
        fenced_client = {
            p["name"] for p in rec["phases"]
            if p["path"].startswith("tick/decide/grpc/") and p["fenced"]
        }
        assert {"pack", "rpc", "unpack", "packing_post"} <= fenced_client
    finally:
        server.stop(grace=None)


def test_remote_decide_matches_local(plugin):
    rng = random.Random(9)
    groups = [random_group(rng, gi) for gi in range(12)]
    cluster = pack_cluster(groups, pad_pods=512, pad_nodes=256, pad_groups=16)
    remote = plugin.decide_arrays(cluster, NOW)
    local = kernel.decide_jit(cluster, np.int64(NOW))
    np.testing.assert_array_equal(remote.status, np.asarray(local.status))
    np.testing.assert_array_equal(remote.nodes_delta, np.asarray(local.nodes_delta))
    np.testing.assert_array_equal(remote.cpu_percent, np.asarray(local.cpu_percent))
    np.testing.assert_array_equal(
        remote.scale_down_order, np.asarray(local.scale_down_order)
    )
    np.testing.assert_array_equal(remote.reap_mask, np.asarray(local.reap_mask))


def test_controller_over_grpc_backend(plugin):
    """Full controller tick with the decision served over the socket."""
    from tests.test_controller import World, make_opts
    from escalator_tpu.testsupport.builders import (
        NodeOpts, PodOpts, build_test_nodes, build_test_pods,
    )

    backend = GrpcBackend(plugin.address)
    pods = build_test_pods(10, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key="customer", node_selector_value="buildeng"))
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    w = World(make_opts(), nodes=nodes, pods=pods, backend=backend)
    w.tick()
    assert w.state.scale_delta == 6
    assert w.group.target_size() == 8


def test_fallback_when_server_unreachable():
    """The north-star CPU fallback: plugin down -> golden backend, same answer."""
    from escalator_tpu.testsupport.builders import (
        NodeOpts, PodOpts, build_test_nodes, build_test_pods,
    )

    backend = GrpcBackend("127.0.0.1:1", timeout_sec=0.5)  # nothing listens here
    pods = build_test_pods(4, PodOpts(cpu=[500], mem=[10**8]))
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    cfg = sem.GroupConfig(
        min_nodes=0, max_nodes=100, taint_lower_percent=30, taint_upper_percent=45,
        scale_up_percent=70, slow_removal_rate=1, fast_removal_rate=2,
    )
    out = backend.decide([(pods, nodes, cfg, sem.GroupState())], NOW)
    assert out[0].decision.status == sem.DecisionStatus.OK
    # 2000/2000 = 100% -> ceil(2*(100-70)/70) = 1
    assert out[0].decision.nodes_delta == 1
