"""Device-state snapshot/restore + deterministic replay (round-11 tentpole).

Three layers under test:

- the snapshot FILE (ops/snapshot.py): bit-exact round trip, atomic write,
  and loud rejection of every corruption class (magic, version, truncation,
  bit flips, missing leaves);
- the decider warm start (device_state.restore_decider): a restored
  ``IncrementalDecider`` continues BIT-EXACTLY from where the snapshot was
  taken — including ordered ticks off the restored order state — and the
  post-restore background audit self-checks the adopted aggregates;
- deterministic replay (observability/replay.py + the debug-replay CLI):
  a recorded input ring re-executes from a snapshot to identical per-tick
  crc32 decision digests, and divergence is reported, not swallowed.
"""

import json
import os

import numpy as np
import pytest

from escalator_tpu.analysis.registry import NOW, representative_cluster
from escalator_tpu.observability import replay
from escalator_tpu.ops import snapshot as snaplib
from escalator_tpu.ops.device_state import (
    DeviceClusterCache,
    IncrementalDecider,
    restore_decider,
)
from escalator_tpu.ops.order_tail import validate_order_state


@pytest.fixture(autouse=True)
def _input_log_hygiene():
    """Recording is process-global; every test starts and ends clean."""
    replay.INPUT_LOG.set_enabled(False)
    replay.INPUT_LOG.clear()
    yield
    replay.INPUT_LOG.set_enabled(False)
    replay.INPUT_LOG.clear()


def make_decider(seed=0, **kw):
    host = representative_cluster(seed=seed)
    cache = DeviceClusterCache(host)
    kw.setdefault("refresh_every", 0)
    kw.setdefault("background", False)
    inc = IncrementalDecider(cache, **kw)
    return host, cache, inc


def churn(host, rng, n=4):
    """Mutate a few pod lanes in the HOST arrays in place; returns the dirty
    slot lists the gather consumes (the cache's host views alias these)."""
    P = host.pods.valid.shape[0]
    idx = np.unique(rng.integers(0, P, n))
    host.pods.cpu_milli[idx] = rng.integers(100, 8000, len(idx))
    return idx.astype(np.int64), np.empty(0, np.int64)


def run_tick(host, cache, inc, rng, t, tainted_any=True, record=True):
    ps, ns = churn(host, rng)
    inc.apply_gathered(cache.gather_deltas(ps, ns))
    return inc.decide(NOW + t, tainted_any, _record=record)


def assert_outputs_equal(a, b, msg=""):
    for f in a.__dataclass_fields__:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
            err_msg=f"{msg}: field {f}")


class TestSnapshotFile:
    def _leaves(self):
        rng = np.random.default_rng(7)
        return {
            "a.int": rng.integers(-5, 5, 17).astype(np.int64),
            "b.bool": rng.random(9) < 0.5,
            "c.float": rng.random(6),
            "d.i32": rng.integers(0, 100, (3, 4)).astype(np.int32),
        }

    def test_round_trip_bit_exact(self, tmp_path):
        leaves = self._leaves()
        meta = {"tick": 12, "pod_capacity": 16}
        path = snaplib.write_snapshot(
            str(tmp_path / "s.snap"), leaves, meta)
        got, got_meta = snaplib.read_snapshot(path)
        assert got_meta["tick"] == 12 and got_meta["pod_capacity"] == 16
        assert set(got) == set(leaves)
        for k, v in leaves.items():
            assert got[k].dtype == np.asarray(v).dtype
            np.testing.assert_array_equal(got[k], v, err_msg=k)

    def test_atomic_write_leaves_no_tmp_debris(self, tmp_path):
        path = str(tmp_path / "s.snap")
        snaplib.write_snapshot(path, self._leaves(), {})
        assert os.listdir(tmp_path) == ["s.snap"]

    def test_missing_file_is_file_not_found(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            snaplib.read_snapshot(str(tmp_path / "absent.snap"))

    @pytest.mark.parametrize("mutilate,match", [
        (lambda b: b"NOPE" + b[4:], "bad magic"),
        (lambda b: b[: len(b) // 2], "truncated|payload"),
        (lambda b: b[:-1], "payload"),
        (lambda b: b + b"x", "payload"),
    ])
    def test_structural_corruption_detected(self, tmp_path, mutilate, match):
        path = str(tmp_path / "s.snap")
        snaplib.write_snapshot(path, self._leaves(), {})
        blob = open(path, "rb").read()
        open(path, "wb").write(mutilate(blob))
        with pytest.raises(snaplib.SnapshotCorruptError, match=match):
            snaplib.read_snapshot(path)

    def test_payload_bit_flip_fails_leaf_crc(self, tmp_path):
        path = str(tmp_path / "s.snap")
        snaplib.write_snapshot(path, self._leaves(), {})
        blob = bytearray(open(path, "rb").read())
        blob[-3] ^= 0x40   # inside the last leaf's payload
        open(path, "wb").write(bytes(blob))
        with pytest.raises(snaplib.SnapshotCorruptError, match="crc32"):
            snaplib.read_snapshot(path)

    def test_unknown_version_rejected(self, tmp_path):
        path = str(tmp_path / "s.snap")
        snaplib.write_snapshot(path, self._leaves(), {})
        blob = open(path, "rb").read()
        off = len(snaplib.SNAPSHOT_MAGIC)
        hlen = int.from_bytes(blob[off:off + 8], "big")
        header = json.loads(blob[off + 8:off + 8 + hlen])
        header["version"] = 99
        hraw = json.dumps(header).encode()
        open(path, "wb").write(
            snaplib.SNAPSHOT_MAGIC + len(hraw).to_bytes(8, "big") + hraw
            + blob[off + 8 + hlen:])
        with pytest.raises(snaplib.SnapshotCorruptError, match="version"):
            snaplib.read_snapshot(path)

    def test_missing_leaf_is_named(self):
        host, cache, inc = make_decider(seed=3)
        rng = np.random.default_rng(3)
        run_tick(host, cache, inc, rng, 0)
        leaves, _meta = inc.snapshot_state()
        del leaves["aggs.cpu_req"]
        with pytest.raises(snaplib.SnapshotCorruptError,
                           match="aggs.cpu_req"):
            snaplib.leaves_to_state(leaves)


class TestOrderStateValidation:
    def _state(self, n=8):
        rng = np.random.default_rng(0)
        return (rng.integers(0, 5, n).astype(np.int64),
                rng.integers(0, 5, n).astype(np.int64),
                rng.integers(0, 5, n).astype(np.int64),
                np.random.default_rng(1).permutation(n).astype(np.int32))

    def test_valid_state_passes(self):
        validate_order_state(*self._state(), num_lanes=8)

    def test_non_permutation_rejected(self):
        m, k1, k2, perm = self._state()
        perm[0] = perm[1]
        with pytest.raises(ValueError, match="permutation"):
            validate_order_state(m, k1, k2, perm, num_lanes=8)

    def test_wrong_shape_and_dtype_rejected(self):
        m, k1, k2, perm = self._state()
        with pytest.raises(ValueError, match="shape"):
            validate_order_state(m[:-1], k1, k2, perm, num_lanes=8)
        with pytest.raises(ValueError, match="dtype"):
            validate_order_state(m.astype(np.int32), k1, k2, perm,
                                 num_lanes=8)


class TestDeciderSnapshotRestore:
    def test_snapshot_before_first_decide_is_none(self):
        _host, _cache, inc = make_decider(seed=5)
        assert inc.snapshot_state() is None

    def test_restored_decider_continues_bit_exactly(self):
        """The failover core: run, snapshot, keep running; a decider
        restored from the snapshot and fed the SAME subsequent deltas
        produces bit-identical outputs on every tick — ordered ticks (off
        the restored order state) included."""
        host, cache, inc = make_decider(seed=11)
        rng = np.random.default_rng(11)
        for t in range(4):
            run_tick(host, cache, inc, rng, t)   # tainted: order state seeds
        assert inc._order_state is not None
        leaves, meta = inc.snapshot_state()
        assert meta["tick"] == 4

        _cache2, inc2 = restore_decider(leaves, meta, refresh_every=0,
                                        background=False)
        assert inc2.restored and inc2._ticks == 4
        assert inc2._order_state is not None
        for t in range(4, 10):
            ps, ns = churn(host, rng)
            gathered = cache.gather_deltas(ps, ns)
            inc.apply_gathered(gathered)
            o1, r1 = inc.decide(NOW + t, True)
            inc2.apply_gathered(gathered)
            o2, r2 = inc2.decide(NOW + t, True)
            assert r1 == r2
            assert_outputs_equal(o1, o2, f"tick {t}")

    def test_restore_is_self_checking_post_restore_audit(self):
        host, cache, inc = make_decider(seed=13)
        rng = np.random.default_rng(13)
        run_tick(host, cache, inc, rng, 0)
        leaves, meta = inc.snapshot_state()
        # clean restore: background audit reconciles clean
        _c, inc2 = restore_decider(leaves, meta, refresh_every=0)
        assert inc2.drain_audit()
        # tampered-but-crc-valid aggregates: the audit MUST catch it (this
        # is the corruption class the file-level crc cannot see)
        bad = dict(leaves)
        bad["aggs.mem_req"] = bad["aggs.mem_req"].copy()
        bad["aggs.mem_req"][0] += 1
        _c, inc3 = restore_decider(bad, meta, refresh_every=0,
                                   on_mismatch="repair")
        assert not inc3.drain_audit()

    def test_restore_rejects_inconsistent_meta(self):
        host, cache, inc = make_decider(seed=17)
        rng = np.random.default_rng(17)
        run_tick(host, cache, inc, rng, 0)
        leaves, meta = inc.snapshot_state()
        bad_meta = dict(meta, pod_capacity=meta["pod_capacity"] + 1)
        with pytest.raises(snaplib.SnapshotCorruptError, match="capacit"):
            restore_decider(leaves, bad_meta)

    def test_restore_rejects_corrupt_order_state(self):
        host, cache, inc = make_decider(seed=19)
        rng = np.random.default_rng(19)
        for t in range(2):
            run_tick(host, cache, inc, rng, t)
        leaves, meta = inc.snapshot_state()
        assert "order.perm" in leaves
        bad = dict(leaves)
        bad["order.perm"] = bad["order.perm"].copy()
        bad["order.perm"][0] = bad["order.perm"][1]   # not a permutation
        with pytest.raises(snaplib.SnapshotCorruptError, match="order state"):
            restore_decider(bad, meta)


class TestSnapshotWriter:
    def test_cadence_and_latest_path(self, tmp_path):
        host, cache, inc = make_decider(seed=23)
        rng = np.random.default_rng(23)
        w = snaplib.SnapshotWriter(str(tmp_path / "snaps"), every=2)
        started = []
        for t in range(5):
            run_tick(host, cache, inc, rng, t)
            started.append(w.maybe_checkpoint(inc))
        w.drain()
        assert started == [False, True, False, True, False]
        assert w.checkpoints == 2
        leaves, meta = snaplib.read_snapshot(w.path)
        assert meta["tick"] == 4   # the second cadence point
        # and the file restores
        _c, inc2 = restore_decider(leaves, meta, refresh_every=0)
        assert inc2.drain_audit()

    def test_pre_decide_checkpoint_skipped(self, tmp_path):
        _host, _cache, inc = make_decider(seed=29)
        w = snaplib.SnapshotWriter(str(tmp_path), every=1)
        assert not w.maybe_checkpoint(inc)
        assert not os.path.exists(w.path)

    def test_disabled_cadence_never_writes(self, tmp_path):
        host, cache, inc = make_decider(seed=31)
        rng = np.random.default_rng(31)
        run_tick(host, cache, inc, rng, 0)
        w = snaplib.SnapshotWriter(str(tmp_path), every=0)
        for _ in range(3):
            assert not w.maybe_checkpoint(inc)
        assert w.maybe_checkpoint(inc, force=True)
        w.drain()
        assert os.path.exists(w.path)


class TestDeterministicReplay:
    def _record_run(self, tmp_path, ticks=6, snap_at=3):
        host, cache, inc = make_decider(seed=37)
        rng = np.random.default_rng(37)
        replay.INPUT_LOG.set_enabled(True)
        path = None
        digests = []
        for t in range(ticks):
            if t == snap_at:
                leaves, meta = inc.snapshot_state()
                path = snaplib.write_snapshot(
                    str(tmp_path / "base.snap"), leaves, meta)
            out, _ = run_tick(host, cache, inc, rng, t,
                              tainted_any=(t % 2 == 0))
            digests.append(replay.decision_digest(out))
        replay.INPUT_LOG.set_enabled(False)
        return path, replay.INPUT_LOG.snapshot(), digests

    def test_replay_reproduces_digests(self, tmp_path):
        path, entries, digests = self._record_run(tmp_path)
        assert len(entries) == 6
        report = replay.replay_ring(entries, snapshot_path=path)
        assert report["ok"], report["divergent"]
        assert report["replayed"] == 3 and report["skipped_older"] == 3
        assert [t["digest"] for t in report["ticks"]] == digests[3:]

    def test_replay_reports_divergence(self, tmp_path):
        path, entries, _ = self._record_run(tmp_path)
        entries[-1] = dict(entries[-1], digest="00000000")
        report = replay.replay_ring(entries, snapshot_path=path)
        assert not report["ok"]
        assert [d["tick"] for d in report["divergent"]] == [entries[-1]["tick"]]

    def test_replay_rejects_gaps(self, tmp_path):
        path, entries, _ = self._record_run(tmp_path)
        del entries[4]   # a tick after the snapshot goes missing
        with pytest.raises(ValueError, match="gap"):
            replay.replay_ring(entries, snapshot_path=path)

    def test_dump_carries_tick_inputs(self, tmp_path):
        from escalator_tpu.observability import RECORDER

        _path, entries, _ = self._record_run(tmp_path)
        assert entries
        doc = RECORDER.as_dump("test")
        assert "tick_inputs" in doc
        assert {e["tick"] for e in doc["tick_inputs"]} >= {
            e["tick"] for e in entries}

    def test_debug_replay_cli_end_to_end(self, tmp_path, capsys):
        from escalator_tpu.cli import main
        from escalator_tpu.observability import RECORDER

        path, entries, _ = self._record_run(tmp_path)
        dump_path = str(tmp_path / "ring.json")
        RECORDER.dump(dump_path, reason="test")
        rc = main(["debug-replay", "--dump", dump_path,
                   "--snapshot", path])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["ok"] and report["replayed"] == 3
        # divergence -> exit 1
        doc = json.load(open(dump_path))
        doc["tick_inputs"][-1]["digest"] = "00000000"
        json.dump(doc, open(dump_path, "w"))
        rc = main(["debug-replay", "--dump", dump_path,
                   "--snapshot", path, "--output",
                   str(tmp_path / "report.json")])
        assert rc == 1
        # a dump without inputs -> exit 2
        doc.pop("tick_inputs")
        json.dump(doc, open(dump_path, "w"))
        assert main(["debug-replay", "--dump", dump_path,
                     "--snapshot", path]) == 2
