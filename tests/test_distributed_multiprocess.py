"""Real multi-process jax.distributed: 2 CPU processes form one fleet.

The reference's only multi-process story is active/passive leader election
(SURVEY.md §2.7); escalator-tpu's compute plane scales out with
jax.distributed + a hybrid (dcn, ici) mesh. This spawns two actual worker
processes that join one coordinator, build the global mesh (one dcn row per
host), and agree on a staged psum — the multi-host communication backend
validated end-to-end, not just shape-checked.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_dist_worker.py")

#: Error signatures of a jax/jaxlib CPU build WITHOUT multiprocess collective
#: support (jax 0.4.37's CPU backend raises the first at compile time; newer
#: builds route cross-host CPU collectives through Gloo/MPI and pass). This is
#: a missing CAPABILITY of the installed wheel, not a bug in this repo's fleet
#: code — the same workers pass on builds that ship the collective backend —
#: so it skips rather than fails.
_NO_MULTIPROCESS_CPU_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "CollectivesInterface not available",
)


def _skip_if_unsupported_cpu_collectives(outs):
    """Capability probe on the worker output: the workers themselves are the
    only reliable probe (support depends on how jaxlib was built, which no
    version check captures), so the probe inspects their failure mode."""
    for out in outs:
        for marker in _NO_MULTIPROCESS_CPU_MARKERS:
            if marker in out:
                pytest.skip(
                    "installed jax CPU build lacks multiprocess collectives "
                    f"({marker!r}); fleet path needs a jaxlib with a CPU "
                    "collectives backend (gloo/mpi)"
                )


def test_two_process_fleet_staged_psum():
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=100)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out)
    _skip_if_unsupported_cpu_collectives(outs)
    for pid, (p, out) in enumerate(zip(procs, outs, strict=True)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_OK pid={pid} total=6" in out, out
        assert f"WORKER_GRID_OK pid={pid}" in out, out


def test_partial_config_raises():
    """A lone process_id is a broken fleet template, not single-host mode."""
    from escalator_tpu.parallel import distributed

    with pytest.raises(RuntimeError, match="partial distributed configuration"):
        distributed.initialize(process_id=3)
