"""threadlint + lock-witness gate: zero unwaived findings on the clean tree,
PROOF each rule detects the regression class it was built for, and the PR-11
deadlock shape reconstructed against the runtime witness.

Mirrors tests/test_jaxlint.py: the zero-findings half is the CI invariant
(`make analyze` / the threadlint CI job block on it); the mutation half
re-introduces each hazard through ``run_threadlint(sources=...)`` — the
re-prep-from-dispatch lock inversion, the dropped wait timeout, the unlocked
guarded write, the bare Lock() — and asserts the expected rule fires.

Everything here is source-level or stub-engine: no compiles, no device work
(tier-1 time neutrality).
"""

import threading
import time
import types

import pytest

from escalator_tpu.analysis import concurrency, lockwitness
from escalator_tpu.analysis.lockwitness import LockOrderViolation
from escalator_tpu.analysis.threadlint import run_threadlint

SERVICE = "escalator_tpu/fleet/service.py"
SCHEDULER = "escalator_tpu/fleet/scheduler.py"
SERVER = "escalator_tpu/plugin/server.py"


def _unwaived(report, rule):
    return [f for f in report.unwaived if f.rule == rule]


# ---------------------------------------------------------------------------
# The gate: clean tree -> zero unwaived findings
# ---------------------------------------------------------------------------


def test_clean_tree_has_zero_unwaived_findings():
    report = run_threadlint()
    assert not report.unwaived, "\n".join(
        f"{f.rule} {f.site}:{f.line} {f.summary}" for f in report.unwaived
    )
    assert set(report.modules) == set(concurrency.COVERED_MODULES)


def test_unlocked_epoch_bump_is_waived_not_clean():
    """The documented unlocked epoch write must be VISIBLE as a waived T3
    finding — if it disappears (the bump moved under _host, or the attr was
    renamed), the inline waiver is stale and should be pruned."""
    report = run_threadlint()
    epoch = [f for f in report.findings
             if f.rule == "T3" and "_epoch" in f.summary]
    assert epoch, "the unlocked epoch bump no longer produces its T3 " \
                  "finding; remove the inline waiver in fleet/service.py"
    assert all(f.waived for f in epoch)


def test_contract_registry_is_consistent():
    ranks = [c.rank for c in concurrency.CONTRACTS]
    assert len(set(ranks)) == len(ranks)
    # the documented fleet order: cv below exec below host below device,
    # observability leaves above the whole fleet path, chaos on top
    by = concurrency.CONTRACTS_BY_NAME
    assert (by["scheduler.cv"].rank < by["engine.exec"].rank
            < by["engine.host"].rank < by["engine.device"].rank
            < by["journal.ring"].rank < by["chaos.rules"].rank)
    for c in concurrency.CONTRACTS:
        assert c.module in concurrency.COVERED_MODULES


# ---------------------------------------------------------------------------
# Mutation tests: each hazard class, re-introduced, must be detected
# ---------------------------------------------------------------------------


def test_mutation_direct_lock_inversion_fires_T1():
    src = (
        "class FleetEngine:\n"
        "    def bad(self):\n"
        "        with self._host:\n"
        "            with self._exec_lock:\n"
        "                pass\n"
    )
    report = run_threadlint(sources={SERVICE: src})
    t1 = _unwaived(report, "T1")
    assert t1, report.findings
    assert "engine.exec" in t1[0].summary and "engine.host" in t1[0].summary


def test_mutation_pr11_re_prep_from_dispatch_fires_T1_transitively():
    """The PR-11 deadlock shape: the dispatch path, already under the host
    condition, calls back into a prep helper that takes the exec lock — the
    inversion hides one call away, so only the AST call graph sees it."""
    src = (
        "class FleetEngine:\n"
        "    def _dispatch(self):\n"
        "        with self._host:\n"
        "            self._re_prep()\n"
        "    def _re_prep(self):\n"
        "        with self._exec_lock:\n"
        "            pass\n"
    )
    report = run_threadlint(sources={SERVICE: src})
    t1 = _unwaived(report, "T1")
    assert t1, report.findings
    assert any("_re_prep" in f.detail for f in t1), t1


def test_mutation_dropped_wait_timeout_fires_T2():
    src = (
        "class FleetScheduler:\n"
        "    def _run(self):\n"
        "        with self._cv:\n"
        "            self._cv.wait()\n"
    )
    report = run_threadlint(sources={SCHEDULER: src})
    t2 = _unwaived(report, "T2")
    assert t2 and "wait" in t2[0].summary, report.findings
    # the shipped shape — bounded, predicate-checked — stays clean
    timed = src.replace(".wait()", ".wait(0.05)")
    assert not _unwaived(run_threadlint(sources={SCHEDULER: timed}), "T2")


def test_mutation_unbounded_result_under_lock_fires_T2():
    src = (
        "class FleetEngine:\n"
        "    def execute(self, fut):\n"
        "        with self._exec_lock:\n"
        "            fut.result()\n"
    )
    report = run_threadlint(sources={SERVICE: src})
    t2 = _unwaived(report, "T2")
    assert t2 and "engine.exec" in t2[0].summary, report.findings


def test_mutation_grpc_call_under_lock_fires_T2():
    src = (
        "class _ComputeService:\n"
        "    def tick(self, req):\n"
        "        with self._stats_lock:\n"
        "            return self._stub.Decide(req)\n"
    )
    report = run_threadlint(sources={SERVER: src})
    t2 = _unwaived(report, "T2")
    assert t2 and "gRPC" in t2[0].summary, report.findings


def test_mutation_unlocked_guarded_write_fires_T3():
    """The other half of the PR-11 class: the dispatch path bumping the
    epoch without the host condition AND without the documented waiver."""
    src = (
        "class FleetEngine:\n"
        "    def _dispatch(self):\n"
        "        self._epoch += 1\n"
    )
    report = run_threadlint(sources={SERVICE: src})
    t3 = _unwaived(report, "T3")
    assert t3 and "_epoch" in t3[0].summary, report.findings
    # under its owning lock the same write is clean
    locked = (
        "class FleetEngine:\n"
        "    def _dispatch(self):\n"
        "        with self._host:\n"
        "            self._epoch += 1\n"
    )
    assert not _unwaived(run_threadlint(sources={SERVICE: locked}), "T3")


def test_mutation_bare_lock_construction_fires_T4():
    src = (
        "class FleetEngine:\n"
        "    def __init__(self):\n"
        "        self._extra_lock = threading.Lock()\n"
    )
    report = run_threadlint(sources={SERVICE: src})
    t4 = _unwaived(report, "T4")
    assert t4 and "threading.Lock" in t4[0].summary, report.findings


def test_mutation_undeclared_thread_fires_T4():
    anon = (
        "def _spawn():\n"
        "    import threading\n"
        "    threading.Thread(target=print).start()\n"
    )
    report = run_threadlint(sources={SCHEDULER: anon})
    assert any("without a literal name" in f.summary
               for f in _unwaived(report, "T4")), report.findings
    rogue = anon.replace("target=print",
                         "target=print, name=\"rogue-worker\"")
    report = run_threadlint(sources={SCHEDULER: rogue})
    assert any("rogue-worker" in f.summary
               for f in _unwaived(report, "T4")), report.findings
    declared = anon.replace(
        "target=print", "target=print, name=\"escalator-tpu-fleet-prep\"")
    assert not _unwaived(run_threadlint(sources={SCHEDULER: declared}), "T4")


# ---------------------------------------------------------------------------
# Waiver mechanics (mirroring jaxlint's ledger semantics)
# ---------------------------------------------------------------------------


def test_inline_waiver_suppresses_but_stays_visible():
    src = (
        "class FleetEngine:\n"
        "    def _dispatch(self):\n"
        "        # threadlint: waive[T3] testing the inline syntax\n"
        "        self._epoch += 1\n"
    )
    report = run_threadlint(sources={SERVICE: src})
    t3 = [f for f in report.findings if f.rule == "T3"]
    assert t3 and all(f.waived for f in t3)
    assert t3[0].waiver_reason == "testing the inline syntax"
    # the waiver is RULE-scoped: a waive[T1] comment does not cover T3
    wrong = src.replace("waive[T3]", "waive[T1]")
    assert _unwaived(run_threadlint(sources={SERVICE: wrong}), "T3")


def test_ledger_waiver_matches_rule_and_site_pattern():
    src = (
        "class FleetEngine:\n"
        "    def _dispatch(self):\n"
        "        self._epoch += 1\n"
    )
    waiver = [{"rule": "T3", "site": "escalator_tpu/fleet/*",
               "reason": "ledger test"}]
    report = run_threadlint(sources={SERVICE: src}, extra_waivers=waiver)
    t3 = [f for f in report.findings if f.rule == "T3"]
    assert t3 and all(f.waived for f in t3)
    miss = [{"rule": "T3", "site": "escalator_tpu/plugin/*", "reason": "x"}]
    assert _unwaived(run_threadlint(sources={SERVICE: src},
                                    extra_waivers=miss), "T3")


# ---------------------------------------------------------------------------
# The runtime witness (lockwitness)
# ---------------------------------------------------------------------------


@pytest.fixture
def witness(monkeypatch):
    """Arm the witness and return the pre-test VIOLATIONS length; truncates
    any violations this test appended on the way out."""
    monkeypatch.setenv("ESCALATOR_TPU_LOCK_WITNESS", "1")
    base = len(lockwitness.VIOLATIONS)
    yield base
    del lockwitness.VIOLATIONS[base:]


def test_witness_disarmed_factories_return_plain_primitives(monkeypatch):
    monkeypatch.delenv("ESCALATOR_TPU_LOCK_WITNESS", raising=False)
    lk = lockwitness.make_lock("engine.exec")
    assert isinstance(lk, type(threading.Lock()))
    cv = lockwitness.make_condition("engine.host")
    assert isinstance(cv, threading.Condition)


def test_witness_construction_requires_a_contract():
    with pytest.raises(KeyError):
        lockwitness.make_lock("engine.unknown")
    with pytest.raises(TypeError):
        lockwitness.make_lock("engine.host")   # declared as a condition


def test_witness_ascending_order_is_clean(witness):
    ex = lockwitness.make_lock("engine.exec")
    host = lockwitness.make_condition("engine.host")
    dev = lockwitness.make_lock("engine.device")
    with ex, host, dev:
        assert lockwitness.held_stack() == [
            "engine.exec", "engine.host", "engine.device"]
    assert lockwitness.held_stack() == []
    assert len(lockwitness.VIOLATIONS) == witness


def test_witness_out_of_rank_raises_before_acquiring(witness):
    host = lockwitness.make_condition("engine.host")
    ex = lockwitness.make_lock("engine.exec")
    with host:
        with pytest.raises(LockOrderViolation):
            with ex:
                pass
    rec = lockwitness.VIOLATIONS[-1]
    assert rec["acquiring"] == "engine.exec"
    assert rec["held"] == ["engine.host"]
    # the check fired BEFORE the underlying acquire: the lock is still free
    # (a raise after acquiring would wedge every later legitimate taker)
    with ex:
        assert lockwitness.held_stack() == ["engine.exec"]
    assert len(lockwitness.VIOLATIONS) == witness + 1


def test_witness_equal_rank_is_a_violation_unless_reentrant_rlock(witness):
    a = lockwitness.RankedLock("engine.exec", 20, "lock")
    with a:
        with pytest.raises(LockOrderViolation):
            a.acquire()
    rl = lockwitness.RankedLock("engine.exec", 20, "rlock")
    with rl:
        with rl:           # declared-reentrant self-acquisition: exempt
            pass
    del lockwitness.VIOLATIONS[witness:]


def test_witness_condition_wait_keeps_rank_context(witness):
    host = lockwitness.make_condition("engine.host")
    woke = []

    def waiter():
        with host:
            host.wait(timeout=2.0)
            woke.append(lockwitness.held_stack())

    t = threading.Thread(target=waiter, name="escalator-test-waiter")
    t.start()
    time.sleep(0.05)
    with host:
        host.notify_all()
    t.join(timeout=5)
    assert woke == [["engine.host"]]
    assert len(lockwitness.VIOLATIONS) == witness


# ---------------------------------------------------------------------------
# The PR-11 regression, end to end: the deadlock shape trips the armed
# witness; the SHIPPED scheduler/engine code path stays violation-free.
# ---------------------------------------------------------------------------


def test_pr11_grow_waiting_prep_shape_trips_the_witness(witness):
    """Reconstruct the PR-11 hang as lock operations: the prep thread
    holds the host condition (tenant grow) while the dispatch path tries
    to re-enter prep through the exec lock it still owes — with ranked
    locks the inversion raises instantly instead of deadlocking."""
    ex = lockwitness.make_lock("engine.exec")
    host = lockwitness.make_condition("engine.host")
    with host:                       # prep: growing a tenant under _host
        with pytest.raises(LockOrderViolation):
            ex.acquire()             # dispatch re-entering prep: inverted
    assert len(lockwitness.VIOLATIONS) == witness + 1
    assert lockwitness.VIOLATIONS[-1]["acquiring"] == "engine.exec"


def test_pipelined_scheduler_soak_is_clean_under_witness(monkeypatch):
    """A stub-engine pipelined scheduler run (prep + dispatch worker pair,
    real FleetScheduler locks constructed ranked): zero violations. This is
    the cheap always-on arm of the witness; the fleet soak and chaos-soak CI
    run it against the real engine."""
    from escalator_tpu.fleet import FleetScheduler

    monkeypatch.setenv("ESCALATOR_TPU_LOCK_WITNESS", "1")
    base = len(lockwitness.VIOLATIONS)

    class _TwoStage:
        tenant_count = 0

        def has_tenant(self, tid):
            return False

        def prepare_batch(self, requests):
            return types.SimpleNamespace(
                requests=list(requests), overlap_saved_ms=None, prep_ms=0.0)

        def execute_batch(self, pb):
            return [("decided", r.tenant_id, r.now_sec)
                    for r in pb.requests]

        def release_prepared(self, pb, wait_sec=5.0):
            return True

    sched = FleetScheduler(_TwoStage(), max_batch=2, flush_ms=1.0,
                           queue_limit=64, per_tenant_inflight=4)
    assert sched.pipelined
    assert isinstance(sched._cv, lockwitness.RankedCondition)
    try:
        futs = [sched.submit(f"w{i}", None, i) for i in range(12)]
        for f in futs:
            assert f.result(timeout=10)[0] == "decided"
    finally:
        sched.shutdown()
    assert lockwitness.VIOLATIONS[base:] == []
