"""Golden tests of the pure decision semantics, ported from the reference's tables
(/root/reference/pkg/controller/util_test.go, pkg/k8s/util_test.go)."""


import pytest

from escalator_tpu.core import semantics as sem
from escalator_tpu.k8s import types as k8s
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)


def calc_percentage_usage(pods, nodes):
    """Helper mirroring util_test.go:195-202."""
    mem_req, cpu_req = k8s.calculate_pods_requests_total(pods)
    mem_cap, cpu_cap = k8s.calculate_nodes_capacity_total(nodes)
    return sem.calc_percent_usage(
        cpu_req, mem_req * 1000, cpu_cap, mem_cap * 1000, len(nodes)
    )


class TestCalcPercentUsage:
    """Table from util_test.go:204-302. Quantities are (cpu milli, mem milli)."""

    def test_basic(self):
        assert sem.calc_percent_usage(50, 50, 100, 100, 1) == (50.0, 50.0)

    def test_divide_by_zero(self):
        with pytest.raises(ZeroDivisionError):
            sem.calc_percent_usage(50, 50, 0, 0, 10)

    def test_no_request_nonzero_nodes(self):
        with pytest.raises(ZeroDivisionError):
            sem.calc_percent_usage(0, 0, 0, 0, 1)

    def test_zero_numerator(self):
        assert sem.calc_percent_usage(0, 0, 66, 66, 1) == (0.0, 0.0)

    def test_zero_all(self):
        assert sem.calc_percent_usage(0, 0, 0, 0, 0) == (0.0, 0.0)

    def test_scale_from_zero_sentinel(self):
        cpu, mem = sem.calc_percent_usage(50, 50, 0, 0, 0)
        assert cpu == sem.MAX_FLOAT64
        assert mem == sem.MAX_FLOAT64


class TestCalcScaleUpDelta:
    """Closed-loop property from util_test.go:15-192: after adding the computed delta
    of nodes, utilisation must drop to <= threshold."""

    CASES = [
        # (num_pods, pod_cpu, pod_mem, num_nodes, node_cpu, node_mem, threshold)
        (10, 500, 100, 2, 1000, 4000, 70),
        (10, 500, 2000, 2, 3000, 1000, 70),
        (10, 500, 2000, 2, 3000, 1000, 40),
        (10, 500, 2000, 2, 3000, 1000, 23),
        (10, 500, 2000, 2, 3000, 1000, 3),
        (80, 1000, 1000, 100, 1000, 1000, 70),
        (150, 1000, 1000, 100, 1000, 1000, 110),
    ]

    @pytest.mark.parametrize("case", CASES)
    def test_closed_loop(self, case):
        num_pods, pc, pm, num_nodes, nc, nm, thr = case
        pods = build_test_pods(num_pods, PodOpts(cpu=[pc], mem=[pm]))
        nodes = build_test_nodes(num_nodes, NodeOpts(cpu=nc, mem=nm))

        cpu_pct, mem_pct = calc_percentage_usage(pods, nodes)
        mem_req, cpu_req = k8s.calculate_pods_requests_total(pods)
        try:
            want = sem.calc_scale_up_delta(
                len(nodes), cpu_pct, mem_pct, cpu_req, mem_req * 1000, 0, 0, thr
            )
        except ValueError:
            return
        if want <= 0:
            return

        new_nodes = nodes + build_test_nodes(want, NodeOpts(cpu=nc, mem=nm))
        new_cpu, new_mem = calc_percentage_usage(pods, new_nodes)
        assert new_cpu <= thr
        assert new_mem <= thr

    def test_scale_from_zero_no_cache(self):
        # no cached capacity -> scale up by exactly 1 (util.go:20-24)
        delta = sem.calc_scale_up_delta(
            0, sem.MAX_FLOAT64, sem.MAX_FLOAT64, 5000, 5000 * 1000, 0, 0, 70
        )
        assert delta == 1

    def test_scale_from_zero_with_cache(self):
        # cached 1000m cpu / 1000 bytes mem; 5000m cpu requested; threshold 70
        # -> ceil(5000/1000/70*100) = ceil(7.1428..) = 8
        delta = sem.calc_scale_up_delta(
            0, sem.MAX_FLOAT64, sem.MAX_FLOAT64, 5000, 100 * 1000, 1000, 1000 * 1000, 70
        )
        assert delta == 8

    def test_negative_delta_error(self):
        with pytest.raises(ValueError):
            sem.calc_scale_up_delta(2, 10.0, 10.0, 100, 100, 0, 0, 70)


class TestPodRequestSemantics:
    """Resource request parity with the vendored scheduler logic
    (reference: pkg/k8s/scheduler/types.go:72-89)."""

    def test_init_container_max(self):
        pod = build_test_pods(
            1,
            PodOpts(
                cpu=[2000, 1000],
                mem=[1 * 10**9, 1 * 10**9],
                init_containers_cpu=[2000, 2000],
                init_containers_mem=[1 * 10**9, 3 * 10**9],
            ),
        )[0]
        req = k8s.compute_pod_resource_request(pod)
        assert req.cpu_milli == 3000
        assert req.mem_bytes == 3 * 10**9

    def test_overhead_added(self):
        pod = build_test_pods(
            1, PodOpts(cpu=[1000], mem=[100], cpu_overhead=500, mem_overhead=50)
        )[0]
        req = k8s.compute_pod_resource_request(pod)
        assert req.cpu_milli == 1500
        assert req.mem_bytes == 150

    def test_daemonset_and_static(self):
        ds = build_test_pods(1, PodOpts(cpu=[1], mem=[1], owner="DaemonSet"))[0]
        st = build_test_pods(1, PodOpts(cpu=[1], mem=[1], static=True))[0]
        assert k8s.pod_is_daemonset(ds)
        assert not k8s.pod_is_daemonset(st)
        assert k8s.pod_is_static(st)
        assert not k8s.pod_is_static(ds)


class TestEvaluateNodeGroup:
    def _config(self, **kw):
        base = dict(
            min_nodes=1,
            max_nodes=100,
            taint_lower_percent=30,
            taint_upper_percent=45,
            scale_up_percent=70,
            slow_removal_rate=1,
            fast_removal_rate=2,
        )
        base.update(kw)
        return sem.GroupConfig(**base)

    def test_empty_group_noop(self):
        d = sem.evaluate_node_group([], [], self._config(min_nodes=0), sem.GroupState())
        assert d.status == sem.DecisionStatus.NOOP_EMPTY

    def test_below_min_error(self):
        pods = build_test_pods(1, PodOpts(cpu=[100], mem=[100]))
        d = sem.evaluate_node_group(
            pods, [], self._config(min_nodes=2), sem.GroupState()
        )
        assert d.status == sem.DecisionStatus.ERR_BELOW_MIN

    def test_above_max_error(self):
        nodes = build_test_nodes(5, NodeOpts(cpu=1000, mem=1000))
        d = sem.evaluate_node_group(
            [], nodes, self._config(max_nodes=3), sem.GroupState()
        )
        assert d.status == sem.DecisionStatus.ERR_ABOVE_MAX

    def test_scale_up(self):
        pods = build_test_pods(10, PodOpts(cpu=[500], mem=[100]))
        nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4000))
        d = sem.evaluate_node_group(pods, nodes, self._config(), sem.GroupState())
        assert d.status == sem.DecisionStatus.OK
        # cpu: 5000/2000 = 250%; delta = ceil(2*(250-70)/70) = ceil(5.142..) = 6
        assert d.nodes_delta == 6

    def test_scale_down_fast(self):
        pods = build_test_pods(1, PodOpts(cpu=[100], mem=[100]))
        nodes = build_test_nodes(10, NodeOpts(cpu=1000, mem=1000))
        d = sem.evaluate_node_group(pods, nodes, self._config(), sem.GroupState())
        # 100/10000 = 1% < 30 -> -fast (=2)
        assert d.status == sem.DecisionStatus.OK
        assert d.nodes_delta == -2

    def test_scale_down_slow(self):
        pods = build_test_pods(4, PodOpts(cpu=[1000], mem=[1000]))
        nodes = build_test_nodes(10, NodeOpts(cpu=1000, mem=1000))
        d = sem.evaluate_node_group(pods, nodes, self._config(), sem.GroupState())
        # 40% in [30,45) -> -slow (=1)
        assert d.nodes_delta == -1

    def test_no_action_band(self):
        pods = build_test_pods(5, PodOpts(cpu=[1000], mem=[1000]))
        nodes = build_test_nodes(10, NodeOpts(cpu=1000, mem=1000))
        d = sem.evaluate_node_group(pods, nodes, self._config(), sem.GroupState())
        # 50% in [45,70] -> 0
        assert d.status == sem.DecisionStatus.OK
        assert d.nodes_delta == 0

    def test_locked_returns_requested(self):
        pods = build_test_pods(10, PodOpts(cpu=[1000], mem=[1000]))
        nodes = build_test_nodes(10, NodeOpts(cpu=1000, mem=1000))
        st = sem.GroupState(locked=True, requested_nodes=4)
        d = sem.evaluate_node_group(pods, nodes, self._config(), st)
        assert d.status == sem.DecisionStatus.LOCKED
        assert d.nodes_delta == 4

    def test_forced_min_scale_up(self):
        nodes = build_test_nodes(
            4, NodeOpts(cpu=1000, mem=1000, tainted=True, taint_time_sec=1)
        ) + build_test_nodes(1, NodeOpts(cpu=1000, mem=1000))
        d = sem.evaluate_node_group(
            [], nodes, self._config(min_nodes=3), sem.GroupState()
        )
        assert d.status == sem.DecisionStatus.FORCED_MIN_SCALE_UP
        assert d.nodes_delta == 2  # 3 - 1 untainted

    def test_scale_up_from_zero_untainted(self):
        # all nodes tainted, pods pending -> MaxFloat64 sentinel -> from-zero delta
        nodes = build_test_nodes(
            2, NodeOpts(cpu=1000, mem=1000, tainted=True, taint_time_sec=1)
        )
        pods = build_test_pods(5, PodOpts(cpu=[1000], mem=[1000]))
        st = sem.GroupState()
        d = sem.evaluate_node_group(
            pods, nodes, self._config(min_nodes=0), st
        )
        # cached capacity learned from nodes[0] -> ceil(5000/1000/70*100) = 8
        assert d.status == sem.DecisionStatus.OK
        assert d.nodes_delta == 8

    def test_cached_capacity_updated(self):
        nodes = build_test_nodes(2, NodeOpts(cpu=1234, mem=5678))
        st = sem.GroupState()
        sem.evaluate_node_group([], nodes, self._config(), st)
        assert st.cached_cpu_milli == 1234
        assert st.cached_mem_bytes == 5678

    def test_div_zero_error(self):
        nodes = build_test_nodes(2, NodeOpts(cpu=0, mem=0))
        pods = build_test_pods(1, PodOpts(cpu=[100], mem=[100]))
        d = sem.evaluate_node_group(pods, nodes, self._config(), sem.GroupState())
        assert d.status == sem.DecisionStatus.ERR_DIV_ZERO


class TestFilterNodes:
    def test_tri_partition(self):
        u = build_test_nodes(3, NodeOpts(cpu=1, mem=1))
        t = build_test_nodes(2, NodeOpts(cpu=1, mem=1, tainted=True, taint_time_sec=5))
        c = build_test_nodes(1, NodeOpts(cpu=1, mem=1, cordoned=True))
        untainted, tainted, cordoned = sem.filter_nodes(u + t + c)
        assert [n.name for n in untainted] == [n.name for n in u]
        assert [n.name for n in tainted] == [n.name for n in t]
        assert [n.name for n in cordoned] == [n.name for n in c]

    def test_dry_mode_uses_tracker_and_ignores_cordon(self):
        nodes = build_test_nodes(3, NodeOpts(cpu=1, mem=1, cordoned=True))
        tracker = [nodes[1].name]
        untainted, tainted, cordoned = sem.filter_nodes(
            nodes, dry_mode=True, taint_tracker=tracker
        )
        assert [n.name for n in tainted] == [nodes[1].name]
        assert len(untainted) == 2
        assert cordoned == []


class TestSelectionAndReap:
    def test_oldest_and_newest_first(self):
        nodes = [
            build_test_nodes(1, NodeOpts(cpu=1, mem=1, creation_time_ns=t))[0]
            for t in (50, 10, 30)
        ]
        assert sem.nodes_oldest_first(nodes) == [1, 2, 0]
        assert sem.nodes_newest_first(nodes) == [0, 2, 1]

    def test_reap_rules(self):
        now = 10_000
        mk = lambda **kw: build_test_nodes(
            1, NodeOpts(cpu=1, mem=1, tainted=True, **kw)
        )[0]
        past_soft_empty = mk(taint_time_sec=now - 400)
        before_soft = mk(taint_time_sec=now - 100)
        past_hard = mk(taint_time_sec=now - 1000)
        no_delete = mk(taint_time_sec=now - 1000, no_delete=True)
        # past soft, before hard, NON-empty: waits for hard grace
        # (scale_down.go:72-73 — soft deletes only empty nodes)
        past_soft_busy = mk(taint_time_sec=now - 400)
        tainted = [past_soft_empty, before_soft, past_hard, no_delete,
                   past_soft_busy]

        # a pod keeps past_hard non-empty, but hard grace overrides
        pod = build_test_pods(1, PodOpts(cpu=[1], mem=[1]))[0]
        pod.node_name = past_hard.name
        busy_pod = build_test_pods(1, PodOpts(cpu=[1], mem=[1]))[0]
        busy_pod.node_name = before_soft.name
        soft_busy_pod = build_test_pods(1, PodOpts(cpu=[1], mem=[1]))[0]
        soft_busy_pod.node_name = past_soft_busy.name
        info = k8s.create_node_name_to_info_map(
            [pod, busy_pod, soft_busy_pod], tainted)

        out = sem.reap_eligible(
            tainted, info, soft_grace_sec=300, hard_grace_sec=900, now_unix_sec=now
        )
        assert out == [0, 2]

    def test_clamps(self):
        assert sem.clamp_scale_down(10, 5, 3) == 5
        assert sem.clamp_scale_down(10, 9, 3) == 7
        with pytest.raises(ValueError):
            sem.clamp_scale_down(2, 1, 3)
        assert sem.calculate_nodes_to_add(5, 8, 10) == 2
        assert sem.calculate_nodes_to_add(5, 2, 10) == 5
