"""Streaming ingestion (round 12): event-applier edge cases vs full re-list.

The tentpole's safety contract: the event-maintained store must decide
EXACTLY what a full re-list would, on every tick, through every ugly event
interleaving — pod rebinding across node slot reuse, delete-then-re-add of
the same UID inside one tick window, group add/remove while events are
queued, and randomized soak churn. Every tick's parity is digest-exact
(crc32 over the [G] status/delta columns — layout-independent, so the
slot-keyed store and the packer's group-contiguous layout are comparable).

Also locks the store twins: PyStateStore is bit-identical to the C++
NativeStateStore for the same mutation sequence (columns, dirty order,
packed drain), and the packed drain is bit-identical to the legacy
drain+gather path.
"""

import numpy as np
import pytest

from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.core import semantics as sem
from escalator_tpu.core.arrays import ClusterArrays, pack_cluster, pack_groups
from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.cache import WatchBridge
from escalator_tpu.k8s.listers import relist_group_inputs
from escalator_tpu.native import statestore
from escalator_tpu.native.pystore import PyStateStore

# the parity fixture is shared with bench.py --smoke (ONE world definition,
# so the smoke and this suite assert the same contract)
from escalator_tpu.testsupport.streamworld import (
    GROUPS,
    stream_configs as make_configs,
    stream_filters as make_filters,
    stream_node as node,
    stream_pod as pod,
    stream_world as make_world,
)


class StreamHarness:
    """Event pipeline + decider on one side, re-list reference on the other."""

    def __init__(self, store_kind="numpy", n_groups=2):
        from escalator_tpu.ops.device_state import (
            DeviceClusterCache,
            IncrementalDecider,
        )

        self.client = make_world()
        self.filters = make_filters(GROUPS[:n_groups])
        self.configs = make_configs(n_groups)
        self.states = [sem.GroupState() for _ in range(n_groups)]
        self.store = statestore.make_state_store(
            pod_capacity=256, node_capacity=64, kind=store_kind)
        self.bridge = WatchBridge(self.store, self.filters)
        self.client.subscribe(self.bridge.apply, replay=True)
        pods_v, nodes_v = self.store.as_pod_node_arrays()
        self.groups = pack_groups(
            list(zip(self.configs, self.states, strict=True)), pad_groups=8)
        self.store.drain_dirty()
        self.cache = DeviceClusterCache(ClusterArrays(
            groups=self.groups, pods=pods_v, nodes=nodes_v))
        self.inc = IncrementalDecider(self.cache, refresh_every=0)
        self.inc.decide(1_700_000_000, False)   # bootstrap

    def stream_tick(self, now=1_700_000_000):
        from escalator_tpu.observability.replay import decision_digest

        gathered = self.store.drain_dirty_packed()
        self.inc.apply_gathered(gathered)
        nodes_v = self.store.as_pod_node_arrays()[1]
        tainted_any = bool(
            (np.asarray(nodes_v.valid) & np.asarray(nodes_v.tainted)).any())
        out, _ordered = self.inc.decide(now, tainted_any)
        return decision_digest(out)

    def relist_digest(self, now=1_700_000_000):
        import jax

        from escalator_tpu.observability.replay import decision_digest
        from escalator_tpu.ops.kernel import decide_jit

        gi = relist_group_inputs(
            self.client, self.filters, self.configs, self.states)
        cluster = pack_cluster(gi, pad_pods=512, pad_nodes=64, pad_groups=8)
        out = jax.block_until_ready(decide_jit(
            jax.device_put(cluster), np.int64(now), with_orders=False))
        return decision_digest(out)

    def assert_parity(self, now=1_700_000_000, msg=""):
        got, want = self.stream_tick(now), self.relist_digest(now)
        assert got == want, f"stream {got} != relist {want} {msg}"


# --------------------------------------------------------------- store twins
NATIVE = pytest.mark.skipif(
    not statestore.available(),
    reason=f"native build unavailable: {statestore.unavailable_reason()}",
)


def _drive_store(s, rng):
    s.upsert_pods_batch([f"p{i}" for i in range(40)],
                        rng.integers(0, 4, 40), np.full(40, 500),
                        np.full(40, 10**9), rng.integers(-1, 8, 40))
    s.upsert_nodes_batch([f"n{i}" for i in range(8)], np.arange(8) % 4,
                         np.full(8, 4000), np.full(8, 16 * 10**9),
                         creation_ns=rng.integers(1, 10**12, 8),
                         tainted=rng.integers(0, 2, 8))
    for i in rng.integers(0, 40, 10):
        s.delete_pod(f"p{i}")
    s.delete_node("n3")
    s.upsert_pod("p99", 2, 123, 456, node_slot=1)
    s.upsert_node("n9", 1, 2000, 8 * 10**9)   # reuses n3's slot


@NATIVE
class TestStoreTwins:
    def test_columns_dirty_and_packed_drain_bit_identical(self):
        rng1 = np.random.default_rng(5)
        rng2 = np.random.default_rng(5)
        ns = statestore.NativeStateStore(pod_capacity=64, node_capacity=16)
        ps = PyStateStore(pod_capacity=64, node_capacity=16)
        _drive_store(ns, rng1)
        _drive_store(ps, rng2)
        for a, b in ((ns.pod_views(), ps.pod_views()),
                     (ns.node_views(), ps.node_views())):
            for name in a:
                np.testing.assert_array_equal(a[name], b[name],
                                              err_msg=name)
        pa = ns.drain_dirty_packed()
        pb = ps.drain_dirty_packed()
        np.testing.assert_array_equal(pa[0], pb[0])
        np.testing.assert_array_equal(pa[2], pb[2])
        for f in pa[1].__dataclass_fields__:
            x, y = getattr(pa[1], f), getattr(pb[1], f)
            assert x.dtype == y.dtype, f
            np.testing.assert_array_equal(x, y, err_msg=f)
        for f in pa[3].__dataclass_fields__:
            x, y = getattr(pa[3], f), getattr(pb[3], f)
            assert x.dtype == y.dtype, f
            np.testing.assert_array_equal(x, y, err_msg=f)

    def test_packed_drain_matches_drain_plus_gather(self):
        """The one-crossing packed drain is bit-identical to the legacy
        drain_dirty + _gather_padded path (same buckets, same scratch, same
        pad constants) — the fast path can never decide differently."""
        from escalator_tpu.ops import device_state as ds

        rng1 = np.random.default_rng(6)
        rng2 = np.random.default_rng(6)
        a = statestore.NativeStateStore(pod_capacity=64, node_capacity=16)
        b = statestore.NativeStateStore(pod_capacity=64, node_capacity=16)
        _drive_store(a, rng1)
        _drive_store(b, rng2)
        packed = a.drain_dirty_packed()
        pd, nd = b.drain_dirty()
        pods_v, nodes_v = b.as_pod_node_arrays()
        pidx, pvals = ds._gather_padded(
            pods_v, pd, ds._bucket(len(pd)), b.pod_capacity, ds._POD_PAD)
        nidx, nvals = ds._gather_padded(
            nodes_v, nd, ds._bucket(len(nd)), b.node_capacity, ds._NODE_PAD)
        np.testing.assert_array_equal(packed[0], pidx)
        np.testing.assert_array_equal(packed[2], nidx)
        for f in pvals.__dataclass_fields__:
            np.testing.assert_array_equal(
                getattr(packed[1], f), getattr(pvals, f), err_msg=f)
        for f in nvals.__dataclass_fields__:
            np.testing.assert_array_equal(
                getattr(packed[3], f), getattr(nvals, f), err_msg=f)

    def test_unavailable_reason_none_when_available(self):
        assert statestore.unavailable_reason() is None

    def test_make_state_store_kinds(self):
        assert isinstance(
            statestore.make_state_store(kind="numpy", pod_capacity=64,
                                        node_capacity=16),
            PyStateStore)
        assert statestore.store_kind(
            statestore.make_state_store(kind="native", pod_capacity=64,
                                        node_capacity=16)) == "native"
        with pytest.raises(ValueError):
            statestore.make_state_store(kind="bogus")


# -------------------------------------------------- applier edge-case parity
@pytest.mark.parametrize("store_kind", ["numpy", pytest.param(
    "native", marks=NATIVE)])
class TestApplierEdgeCasesVsRelist:
    def test_pod_rebind_on_node_slot_reuse(self, store_kind):
        """Delete a node whose slot is then reused by a NEW node: pods bound
        to the dead node must not inherit the recycled slot, and pods of
        the new node must bind to it — digest-exact vs re-list."""
        h = StreamHarness(store_kind)
        old_slot = h.store.node_slot("alpha-n1")
        h.client.delete_node("alpha-n1")
        h.client.add_node(node("beta-n9", "beta", creation=99))
        assert h.store.node_slot("beta-n9") == old_slot   # slot reused
        # a pod still claiming the dead node, and one landing on the new one
        h.client.update_pod(pod("alpha-p1", "alpha", node="alpha-n1"))
        h.client.add_pod(pod("beta-p77", "beta", cpu=900, node="beta-n9"))
        h.assert_parity(msg="(slot reuse)")
        # late node re-add heals the dangling binding too
        h.client.add_node(node("alpha-n1", "alpha", creation=123))
        h.assert_parity(msg="(node re-added)")

    def test_delete_then_add_same_uid_one_window(self, store_kind):
        """DELETE + ADD of the same pod UID inside one tick window must land
        as the new pod's values (and exactly once) in the decided state."""
        h = StreamHarness(store_kind)
        victim = [p for p in h.client.list_pods() if p.name == "alpha-p5"][0]
        h.client.remove_pod(victim)
        h.client.add_pod(pod("alpha-p5", "alpha", cpu=2000, mem=4 * 10**9,
                             node="alpha-n0"))
        h.assert_parity(msg="(delete-then-add)")
        # and the reverse order next window: add (update), then delete
        h.client.update_pod(pod("alpha-p5", "alpha", cpu=100))
        h.client.remove_pod(
            [p for p in h.client.list_pods() if p.name == "alpha-p5"][0])
        h.assert_parity(msg="(update-then-delete)")

    def test_group_add_remove_while_events_queued(self, store_kind):
        """Grow the filter set from 1 group to 2 and back while mutations
        keep landing: set_groups + resync re-resolves membership, and every
        tick stays digest-exact vs a re-list under the CURRENT filters."""
        h = StreamHarness(store_kind, n_groups=1)   # only alpha configured
        # beta objects exist in the world but match no group: ignored
        h.client.add_pod(pod("beta-late", "beta", cpu=700))
        h.assert_parity(msg="(single group)")
        # group ADD: beta joins; queued mutations land around the resync
        h.client.update_pod(pod("alpha-p2", "alpha", cpu=800,
                                node="alpha-n2"))
        h.filters = make_filters(GROUPS)
        h.configs = make_configs(2)
        h.states = h.states + [sem.GroupState()]
        h.bridge.set_groups(h.filters, client=h.client)
        h.groups = pack_groups(
            list(zip(h.configs, h.states, strict=True)), pad_groups=8)
        # group rows changed shape-compatibly ([8] pad): ship them with the
        # next batch, the config-dirty compare marks every changed row
        gathered = h.store.drain_dirty_packed()
        h.inc.apply_gathered(gathered, h.groups)
        h.assert_parity(msg="(group added)")
        # group REMOVE: back to alpha-only; beta pods/nodes leave the store
        h.client.update_pod(pod("beta-p1", "beta", cpu=50))
        h.filters = make_filters(GROUPS[:1])
        h.configs = make_configs(1)
        h.states = h.states[:1]
        h.bridge.set_groups(h.filters, client=h.client)
        h.groups = pack_groups(
            list(zip(h.configs, h.states, strict=True)), pad_groups=8)
        gathered = h.store.drain_dirty_packed()
        h.inc.apply_gathered(gathered, h.groups)
        h.assert_parity(msg="(group removed)")

    def test_soak_random_interleavings(self, store_kind):
        """Soak: 20 windows of randomized add/update/delete/taint/group-move
        events, parity asserted after every window."""
        h = StreamHarness(store_kind)
        rng = np.random.default_rng(11)
        now = 1_700_000_000
        for t in range(20):
            for _ in range(int(rng.integers(1, 6))):
                act = rng.integers(0, 5)
                g = GROUPS[int(rng.integers(0, 2))]
                i = int(rng.integers(0, 14))
                if act == 0:
                    h.client.add_pod(pod(
                        f"{g}-extra{int(rng.integers(0, 20))}", g,
                        cpu=int(rng.choice([100, 500, 1100, 2000])),
                        node=f"{g}-n{int(rng.integers(0, 4))}"))
                elif act == 1:
                    h.client.update_pod(pod(
                        f"{g}-p{i}", g,
                        cpu=int(rng.choice([100, 500, 1100, 2000])),
                        node=f"{g}-n{int(rng.integers(0, 4))}"))
                elif act == 2:
                    live = [p for p in h.client.list_pods()
                            if p.name.startswith(f"{g}-extra")]
                    if live:
                        h.client.remove_pod(live[0])
                elif act == 3:
                    # taint flip on a random node (keeps its identity)
                    names = [n.name for n in h.client.list_nodes()
                             if n.labels.get("customer") == g]
                    if names:
                        nd = h.client.get_node(
                            names[int(rng.integers(0, len(names)))]).copy()
                        if nd.taints:
                            nd.taints = []
                        else:
                            nd.taints = [k8s.Taint(
                                key=k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY,
                                value=str(now - 40))]
                        h.client.update_node(nd)
                else:
                    # group move: a pod's selector flips to the other group
                    other = GROUPS[1 - GROUPS.index(g)]
                    h.client.update_pod(pod(f"{g}-p{i}", other))
            h.assert_parity(now + t, msg=f"(soak window {t})")


# ------------------------------------------------------------ resync healing
def test_resync_heals_missed_delete_and_drift():
    """A DELETED event the bridge never saw (simulated by mutating the
    client's internal dict) leaves the store stale; bridge.resync drops the
    stale entry and re-resolves everything — parity restored."""
    h = StreamHarness("numpy")
    with h.client._lock:
        h.client._pods.pop("default/alpha-p3")    # vanish without an event
    # the stream is now stale (still counts alpha-p3); resync reconciles
    stats = h.bridge.resync(h.client)
    assert stats["pods_dropped"] == 1
    assert h.store.pod_slot("default/alpha-p3") == -1
    h.assert_parity(msg="(after resync)")


def test_native_backend_relist_audit_cadence():
    """NativeJaxBackend(relist_audit_every=2): a missed delete heals at the
    audit tick without operator action."""
    from escalator_tpu.controller.native_backend import NativeJaxBackend

    client = make_world()
    backend = NativeJaxBackend(
        client, make_filters(), pod_capacity=256, node_capacity=64,
        incremental=True, refresh_every=0, relist_audit_every=2,
        store_kind="numpy")
    gi = [([], [], cfg, sem.GroupState()) for cfg in make_configs(2)]
    backend.decide(gi, 1_700_000_000)
    with client._lock:
        client._pods.pop("default/beta-p2")       # missed event
    backend.decide(gi, 1_700_000_060)             # tick 2: audit fires
    assert backend.store.pod_slot("default/beta-p2") == -1, (
        "relist audit should have dropped the stale pod")


# ---------------------------------------------- streaming attach + predrain
def test_incremental_backend_attach_event_source_matches_repack():
    """IncrementalJaxBackend.attach_event_source: same decisions as the
    repack backend fed by the listers, across churn ticks."""
    from escalator_tpu.controller.backend import IncrementalJaxBackend

    client = make_world()
    opts = [
        ngmod.NodeGroupOptions(
            name=v, label_key="customer", label_value=v,
            cloud_provider_group_name=f"{v}-asg", min_nodes=0, max_nodes=100,
            taint_upper_capacity_threshold_percent=45,
            taint_lower_capacity_threshold_percent=30,
            scale_up_threshold_percent=70,
            slow_node_removal_rate=1, fast_node_removal_rate=2,
            soft_delete_grace_period="5m", hard_delete_grace_period="15m",
            scale_up_cool_down_period="10m",
        )
        for v in GROUPS
    ]
    streaming = IncrementalJaxBackend(refresh_every=0)
    streaming.attach_event_source(client, opts, pod_capacity=256,
                                  node_capacity=64, store_kind="numpy")
    assert streaming.needs_objects is False
    repack = IncrementalJaxBackend(refresh_every=0)
    filters = make_filters()
    configs = make_configs(2)
    states_a = [sem.GroupState() for _ in range(2)]
    states_b = [sem.GroupState() for _ in range(2)]
    now = 1_700_000_000
    for t in range(4):
        if t == 1:
            client.update_pod(pod("alpha-p0", "alpha", cpu=1500,
                                  node="alpha-n0"))
        if t == 2:
            client.delete_node("beta-n3")
        if t == 3:
            client.add_pod(pod("beta-burst", "beta", cpu=2000))
        # streaming backend needs no objects
        gi_stream = [([], [], configs[g], states_a[g]) for g in range(2)]
        got = streaming.decide(gi_stream, now + t)
        # repack backend walks the (re-listed) object world
        gi_obj = relist_group_inputs(client, filters, configs, states_b)
        want = repack.decide(gi_obj, now + t)
        for gd_got, gd_want in zip(got, want, strict=True):
            assert gd_got.decision.status == gd_want.decision.status, t
            assert (gd_got.decision.nodes_delta
                    == gd_want.decision.nodes_delta), t
            assert (gd_got.decision.num_pods
                    == gd_want.decision.num_pods), t
    # flight record keeps the logical backend name + names the store
    from escalator_tpu import observability as obs

    recs = [r for r in obs.RECORDER.snapshot()
            if r["root"] == "incremental-jax" and r.get("store")]
    assert recs, "no streaming tick records under the logical backend name"
    assert recs[-1]["store"] == "numpy"


def test_predrain_pending_batches_apply_next_tick():
    """Events that arrive during a tick's device window (captured by
    _predrain into pending batches) are applied before the next tick's
    drain, and the next tick stays digest-exact vs re-list."""
    from escalator_tpu.controller.native_backend import NativeJaxBackend

    client = make_world()
    backend = NativeJaxBackend(
        client, make_filters(), pod_capacity=256, node_capacity=64,
        incremental=True, refresh_every=0, store_kind="numpy")
    gi = [([], [], cfg, sem.GroupState()) for cfg in make_configs(2)]
    backend.decide(gi, 1_700_000_000)            # rebuild
    backend.decide(gi, 1_700_000_060)            # steady (fast path)
    # events land "mid-decide": drain them exactly as the overlap hook does
    client.add_pod(pod("alpha-mid1", "alpha", cpu=1200, node="alpha-n1"))
    client.update_pod(pod("beta-p4", "beta", cpu=50))
    backend._predrain()
    assert backend._pending_batches, "predrain captured nothing"
    # more events after the window closes (normal next-tick drain)
    client.add_pod(pod("alpha-mid2", "alpha", cpu=800))
    results = backend.decide(gi, 1_700_000_120)
    # reference: re-list world decided by the golden-equivalent array path
    import jax

    from escalator_tpu.ops.kernel import decide_jit

    gi_rel = relist_group_inputs(
        client, make_filters(), make_configs(2),
        [sem.GroupState() for _ in range(2)])
    cluster = pack_cluster(gi_rel, pad_pods=512, pad_nodes=64, pad_groups=8)
    full = jax.block_until_ready(decide_jit(
        jax.device_put(cluster), np.int64(1_700_000_120), with_orders=False))
    want = np.asarray(full.nodes_delta)
    for g, gd in enumerate(results):
        assert gd.decision.nodes_delta == int(want[g]), g
    assert not backend._pending_batches


# ------------------------------------------------- warm restore (round 18)
def _stream_opts():
    return [
        ngmod.NodeGroupOptions(
            name=v, label_key="customer", label_value=v,
            cloud_provider_group_name=f"{v}-asg", min_nodes=0, max_nodes=100,
            taint_upper_capacity_threshold_percent=45,
            taint_lower_capacity_threshold_percent=30,
            scale_up_threshold_percent=70,
            slow_node_removal_rate=1, fast_node_removal_rate=2,
            soft_delete_grace_period="5m", hard_delete_grace_period="15m",
            scale_up_cool_down_period="10m",
        )
        for v in GROUPS
    ]


def _attach_stream(client, snapdir, pod_capacity=256, node_capacity=64):
    from escalator_tpu.controller.backend import IncrementalJaxBackend

    backend = IncrementalJaxBackend(
        refresh_every=0, snapshot_dir=snapdir, snapshot_every=1)
    backend.attach_event_source(client, _stream_opts(),
                                pod_capacity=pod_capacity,
                                node_capacity=node_capacity,
                                store_kind="numpy")
    return backend


def test_streaming_warm_restore_parity(tmp_path):
    """Round-18 regression for the PR-7/round-11 caveat: after a snapshot
    restore, attach_event_source seeds the store twin from the checkpoint's
    slot-key sidecar instead of falling back to the O(cluster) repack/replay
    bootstrap — the restored process adopts the device state (no rebuild on
    its first tick), its resync marks only objects that changed while no
    leader ran, and every post-restore streamed decision stays parity-exact
    with a cold re-list reference."""
    from escalator_tpu.controller.backend import IncrementalJaxBackend

    snapdir = str(tmp_path / "snaps")
    client = make_world()
    configs = make_configs(2)
    states = [sem.GroupState() for _ in range(2)]
    gi = [([], [], configs[g], states[g]) for g in range(2)]
    now = 1_700_000_000

    first = _attach_stream(client, snapdir)
    for t in range(3):
        first.decide(gi, now + t)
    first._stream._writer.drain()
    assert first._stream._writer.checkpoints >= 1

    # the world moves while no leader runs: one changed pod, one new pod,
    # one deleted node (its pods must rebind to slot -1 on resync)
    client.update_pod(pod("alpha-p0", "alpha", cpu=1500, node="alpha-n0"))
    client.add_pod(pod("beta-late", "beta", cpu=2000))
    client.delete_node("beta-n3")

    second = _attach_stream(client, snapdir)
    stream = second._stream
    assert stream._cache is not None, "warm restore did not adopt the state"
    adopted = stream._cache
    # the resync folded ONLY the changed objects into the first delta batch:
    # 2 changed pods + the deleted node's rebinds, plus every live node
    # (seeded node objects are sentinels; N << P) — NOT the whole pod world
    assert stream.store.pod_dirty_count <= 8
    repack = IncrementalJaxBackend(refresh_every=0)
    states_b = [sem.GroupState() for _ in range(2)]
    for t in range(3, 6):
        if t == 4:
            client.add_pod(pod("alpha-post", "alpha", cpu=900,
                               node="alpha-n1"))
        got = second.decide(gi, now + t)
        gi_obj = relist_group_inputs(
            client, make_filters(), configs, states_b)
        want = repack.decide(gi_obj, now + t)
        for gd_got, gd_want in zip(got, want, strict=True):
            assert gd_got.decision.status == gd_want.decision.status, t
            assert (gd_got.decision.nodes_delta
                    == gd_want.decision.nodes_delta), t
            assert (gd_got.decision.num_pods
                    == gd_want.decision.num_pods), t
    assert stream._cache is adopted, "first warm tick rebuilt instead of adopting"


def test_streaming_warm_restore_smaller_checkpoint_pads_up(tmp_path):
    """Round-20 closure of the round-18 caveat: a checkpoint SMALLER than
    the configured store is a slot remap, not a cold start — the cluster
    leaves pad up to the configured capacities (every new lane a hole, the
    occupied slots keep their indices), the key tables extend with empty
    entries, and the restart warm-adopts with full decision parity."""
    from escalator_tpu.controller.backend import IncrementalJaxBackend

    snapdir = str(tmp_path / "snaps")
    client = make_world()
    configs = make_configs(2)
    states = [sem.GroupState() for _ in range(2)]
    gi = [([], [], configs[g], states[g]) for g in range(2)]
    now = 1_700_000_000

    first = _attach_stream(client, snapdir, pod_capacity=64,
                           node_capacity=16)
    for t in range(2):
        first.decide(gi, now + t)
    first._stream._writer.drain()
    assert first._stream._writer.checkpoints >= 1

    # restart with a LARGER configured store: pre-round-20 this was the
    # "capacities smaller than the configured store" stale cold start
    second = _attach_stream(client, snapdir, pod_capacity=256,
                            node_capacity=64)
    stream = second._stream
    assert stream._cache is not None, "pad-up restore cold-started"
    assert stream._cache.pod_capacity == 256
    assert stream._cache.node_capacity == 64

    client.add_pod(pod("beta-growth", "beta", cpu=1200))
    repack = IncrementalJaxBackend(refresh_every=0)
    got = second.decide(gi, now + 60)
    want = repack.decide(
        relist_group_inputs(client, make_filters(), configs,
                            [sem.GroupState() for _ in range(2)]),
        now + 60)
    for gd_got, gd_want in zip(got, want, strict=True):
        assert gd_got.decision.status == gd_want.decision.status
        assert gd_got.decision.nodes_delta == gd_want.decision.nodes_delta
        assert gd_got.decision.num_pods == gd_want.decision.num_pods


def test_streaming_warm_restore_sidecar_missing_cold_starts(tmp_path):
    """A checkpoint written without the slot-key sidecar (pre-round-18
    writer) cannot replay the store layout: the stream must cold-start —
    loudly, not silently wrong — and still decide parity-exact."""
    from escalator_tpu.ops import snapshot as snaplib

    snapdir = str(tmp_path / "snaps")
    client = make_world()
    configs = make_configs(2)
    states = [sem.GroupState() for _ in range(2)]
    gi = [([], [], configs[g], states[g]) for g in range(2)]

    first = _attach_stream(client, snapdir)
    first.decide(gi, 1_700_000_000)
    first._stream._writer.drain()
    path = first._stream._writer.path
    leaves, meta = snaplib.read_snapshot(path)
    assert "store.keys" in leaves, "checkpoint lost its slot-key sidecar"
    del leaves["store.keys"]
    snaplib.write_snapshot(path, leaves, meta)

    second = _attach_stream(client, snapdir)
    assert second._stream._cache is None   # cold bootstrap
    got = second.decide(gi, 1_700_000_060)
    repack_gi = relist_group_inputs(
        client, make_filters(), configs,
        [sem.GroupState() for _ in range(2)])
    from escalator_tpu.controller.backend import IncrementalJaxBackend

    want = IncrementalJaxBackend(refresh_every=0).decide(
        repack_gi, 1_700_000_060)
    for gd_got, gd_want in zip(got, want, strict=True):
        assert gd_got.decision.nodes_delta == gd_want.decision.nodes_delta
