"""Sharded decision parity: shard_map over an 8-device CPU mesh must reproduce the
unsharded kernel (and hence the golden model) exactly."""

import random

import numpy as np
import jax
import pytest

from escalator_tpu.core import semantics as sem
from escalator_tpu.core.arrays import pack_cluster
from escalator_tpu.ops import kernel
from escalator_tpu.parallel import mesh as meshlib

from tests.test_kernel_parity import NOW, random_group


@pytest.fixture(scope="module")
def cpu_mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual CPU devices"
    return meshlib.make_mesh()


def test_sharded_matches_unsharded(cpu_mesh):
    rng = random.Random(11)
    groups = [random_group(rng, gi) for gi in range(64)]

    # Unsharded golden-parity path (separate GroupStates: pack mutates cached_*)
    def fresh(groups):
        return [
            (p, n, c, sem.GroupState(**s.__dict__)) for (p, n, c, s) in groups
        ]

    flat = pack_cluster(fresh(groups))
    ref = kernel.decide_jit(flat, np.int64(NOW))

    sharded, assignment = meshlib.pack_cluster_sharded(fresh(groups), num_shards=8)
    sharded = meshlib.shard_cluster_arrays(sharded, cpu_mesh)
    decider = meshlib.make_sharded_decider(cpu_mesh)
    out = decider(sharded, np.int64(NOW))

    status = np.asarray(out.status)
    delta = np.asarray(out.nodes_delta)
    cpu_pct = np.asarray(out.cpu_percent)
    for s, shard_groups in enumerate(assignment):
        for local, gi in enumerate(shard_groups):
            assert status[s, local] == int(ref.status[gi]), f"group {gi}"
            assert delta[s, local] == int(ref.nodes_delta[gi]), f"group {gi}"
            assert cpu_pct[s, local] == float(ref.cpu_percent[gi]), f"group {gi}"


def test_sharded_selection_orders(cpu_mesh):
    """Scale-down ordering must survive sharding: check one shard's local order maps
    to the golden per-group order."""
    rng = random.Random(5)
    groups = [random_group(rng, gi) for gi in range(16)]
    sharded, assignment = meshlib.pack_cluster_sharded(
        [(p, n, c, sem.GroupState(**s.__dict__)) for (p, n, c, s) in groups],
        num_shards=8,
    )
    sharded_placed = meshlib.shard_cluster_arrays(sharded, cpu_mesh)
    out = meshlib.make_sharded_decider(cpu_mesh)(sharded_placed, np.int64(NOW))

    down = np.asarray(out.scale_down_order)
    offs = np.asarray(out.untainted_offsets)

    for s, shard_groups in enumerate(assignment):
        # shard-local node names in pack order
        local_names = []
        for gi in shard_groups:
            local_names.extend(n.name for n in groups[gi][1])
        for local, gi in enumerate(shard_groups):
            untainted, _, _ = sem.filter_nodes(groups[gi][1])
            want = [untainted[i].name for i in sem.nodes_oldest_first(untainted)]
            got = [
                local_names[i]
                for i in down[s, offs[s, local] : offs[s, local + 1]]
            ]
            assert got == want, f"shard {s} group {gi}"


def test_fleet_totals(cpu_mesh):
    rng = random.Random(3)
    groups = [random_group(rng, gi) for gi in range(16)]
    sharded, _ = meshlib.pack_cluster_sharded(
        [(p, n, c, sem.GroupState(**s.__dict__)) for (p, n, c, s) in groups],
        num_shards=8,
    )
    out = meshlib.make_sharded_decider(cpu_mesh)(
        meshlib.shard_cluster_arrays(sharded, cpu_mesh), np.int64(NOW)
    )
    totals = meshlib.fleet_totals(out)
    assert totals["pods"] == sum(len(p) for p, *_ in groups)
    assert totals["nodes"] == sum(len(n) for _, n, *_ in groups)


class TestHybridMesh:
    def test_hybrid_matches_1d(self, cpu_mesh):
        rng = random.Random(7)
        groups = [random_group(rng, gi) for gi in range(32)]

        def fresh(groups):
            return [
                (p, n, c, sem.GroupState(**vars(s))) for (p, n, c, s) in groups
            ]

        sharded, _ = meshlib.pack_cluster_sharded(fresh(groups), num_shards=8)
        out1 = meshlib.make_sharded_decider(cpu_mesh)(
            meshlib.shard_cluster_arrays(sharded, cpu_mesh), NOW
        )

        hybrid = meshlib.make_hybrid_mesh(jax.devices(), num_hosts=2)
        assert hybrid.axis_names == (meshlib.DCN_AXIS, meshlib.ICI_AXIS)
        assert hybrid.devices.shape == (2, 4)
        out2 = meshlib.make_sharded_decider(hybrid)(
            meshlib.shard_cluster_arrays(sharded, hybrid), NOW
        )
        np.testing.assert_array_equal(
            np.asarray(out1.nodes_delta), np.asarray(out2.nodes_delta)
        )
        np.testing.assert_array_equal(
            np.asarray(out1.status), np.asarray(out2.status)
        )

    def test_fleet_decider_staged_psum(self, cpu_mesh):
        rng = random.Random(13)
        groups = [random_group(rng, gi) for gi in range(16)]
        sharded, _ = meshlib.pack_cluster_sharded(groups, num_shards=8)

        hybrid = meshlib.make_hybrid_mesh(jax.devices(), num_hosts=2)
        placed = meshlib.shard_cluster_arrays(sharded, hybrid)
        out, totals = meshlib.make_fleet_decider(hybrid)(placed, NOW)
        host_totals = meshlib.fleet_totals(out)
        for name, val in host_totals.items():
            assert int(totals[name]) == val, name

    def test_fleet_decider_1d(self, cpu_mesh):
        rng = random.Random(17)
        groups = [random_group(rng, gi) for gi in range(8)]
        sharded, _ = meshlib.pack_cluster_sharded(groups, num_shards=8)
        placed = meshlib.shard_cluster_arrays(sharded, cpu_mesh)
        out, totals = meshlib.make_fleet_decider(cpu_mesh)(placed, NOW)
        assert int(totals["pods"]) == sum(len(p) for p, *_ in groups)

    def test_uneven_hosts_rejected(self):
        with pytest.raises(ValueError):
            meshlib.make_hybrid_mesh(jax.devices(), num_hosts=3)


class TestDistributedInit:
    def test_no_config_stays_single_host(self, monkeypatch):
        from escalator_tpu.parallel import distributed

        for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                    "JAX_PROCESS_ID"):
            monkeypatch.delenv(var, raising=False)
        assert distributed.initialize() is False

    def test_global_hybrid_mesh(self):
        from escalator_tpu.parallel import distributed

        mesh = distributed.global_hybrid_mesh()
        assert mesh.devices.size == 8  # all virtual devices, 1 "host"
        assert mesh.devices.shape[0] == 1


def test_sharded_sweeper_matches_unsharded(cpu_mesh):
    """What-if sweeps sharded like the decision path: [S, G, D] results must
    equal the single-device sweep_deltas per shard block."""
    from escalator_tpu.ops import simulate

    rng = random.Random(23)
    groups = [random_group(rng, gi) for gi in range(32)]

    def fresh(groups):
        return [
            (p, n, c, sem.GroupState(**s.__dict__)) for (p, n, c, s) in groups
        ]

    D = 16
    sharded, assignment = meshlib.pack_cluster_sharded(fresh(groups), num_shards=8)
    placed = meshlib.shard_cluster_arrays(sharded, cpu_mesh)
    sweep = meshlib.make_sharded_sweeper(cpu_mesh, D)(placed)

    # reference: per-shard single-device sweep on the same packed blocks
    leaves, aux = sharded.tree_flatten()
    for s in range(8):
        block = type(sharded).tree_unflatten(aux, [leaf[s] for leaf in leaves])
        ref = simulate.sweep_deltas_jit(jax.device_put(block), num_candidates=D)
        np.testing.assert_array_equal(
            np.asarray(sweep.min_feasible_delta[s]),
            np.asarray(ref.min_feasible_delta),
        )
        np.testing.assert_array_equal(
            np.asarray(sweep.feasible[s]), np.asarray(ref.feasible)
        )
        np.testing.assert_allclose(
            np.asarray(sweep.post_cpu_percent[s]),
            np.asarray(ref.post_cpu_percent),
            rtol=0, atol=0,
        )
