"""Leader election + CLI surface tests."""

import json
import subprocess
import sys
import threading

import pytest

from escalator_tpu.k8s.election import (
    FileResourceLock,
    InMemoryResourceLock,
    LeaderElectionConfig,
    LeaderElector,
    LeaderRecord,
)
from escalator_tpu.utils.clock import MockClock

FAST = LeaderElectionConfig(
    lease_duration_sec=5.0, renew_deadline_sec=3.0, retry_period_sec=0.5
)


class TestLeaderElection:
    def test_single_candidate_becomes_leader(self):
        lock = InMemoryResourceLock()
        e = LeaderElector(lock, FAST, identity="a", clock=MockClock())
        assert e.run(blocking_acquire_timeout=1)
        assert e.is_leader
        assert lock.get().holder == "a"
        e.stop()

    def test_second_candidate_blocks_until_lease_expires(self):
        clock = MockClock()
        lock = InMemoryResourceLock()
        a = LeaderElector(lock, FAST, identity="a", clock=clock)
        assert a.run(blocking_acquire_timeout=1)
        a.stop()  # a stops renewing (simulates death) but holds the lease record

        b = LeaderElector(lock, FAST, identity="b", clock=clock)
        assert not b.run(blocking_acquire_timeout=1)  # lease still fresh
        clock.advance(10)  # lease expires
        assert b.run(blocking_acquire_timeout=1)
        assert lock.get().holder == "b"
        b.stop()

    def test_deposed_callback_on_lost_lease(self):
        clock = MockClock()
        lock = InMemoryResourceLock()
        deposed = threading.Event()
        a = LeaderElector(lock, FAST, identity="a", clock=clock,
                          on_deposed=deposed.set)
        assert a.run(blocking_acquire_timeout=1)
        # usurper takes the lock out from under a
        lock.create_or_update(LeaderRecord("b", clock.now(), clock.now()), "a")
        a._renew_loop()  # run one renew cycle synchronously
        assert deposed.is_set()
        assert not a.is_leader

    def test_file_lock_round_trip(self, tmp_path):
        lock = FileResourceLock(str(tmp_path / "lease.json"))
        assert lock.get() is None
        rec = LeaderRecord("me", 1.0, 2.0)
        assert lock.create_or_update(rec, None)
        got = lock.get()
        assert got.holder == "me" and got.renew_time == 2.0
        # CAS fails for wrong expected holder
        assert not lock.create_or_update(LeaderRecord("you", 3.0, 3.0), "other")
        assert lock.create_or_update(LeaderRecord("you", 3.0, 3.0), "me")
        assert lock.get().holder == "you"


NODEGROUPS_YAML = """
node_groups:
  - name: "buildeng"
    label_key: "customer"
    label_value: "buildeng"
    cloud_provider_group_name: "buildeng-asg"
    min_nodes: 1
    max_nodes: 100
    taint_upper_capacity_threshold_percent: 45
    taint_lower_capacity_threshold_percent: 30
    scale_up_threshold_percent: 70
    slow_node_removal_rate: 1
    fast_node_removal_rate: 2
    soft_delete_grace_period: 5m
    hard_delete_grace_period: 15m
    scale_up_cool_down_period: 10m
"""

SIM_STATE_YAML = """
nodes:
  - {name: n1, labels: {customer: buildeng}, cpu_milli: 1000, mem_bytes: 4000000000}
  - {name: n2, labels: {customer: buildeng}, cpu_milli: 1000, mem_bytes: 4000000000}
pods:
  - {name: p1, cpu_milli: 500, mem_bytes: 1000000000, node_selector: {customer: buildeng}}
  - {name: p2, cpu_milli: 500, mem_bytes: 1000000000, node_selector: {customer: buildeng}}
  - {name: p3, cpu_milli: 500, mem_bytes: 1000000000, node_selector: {customer: buildeng}}
  - {name: p4, cpu_milli: 500, mem_bytes: 1000000000, node_selector: {customer: buildeng}}
"""


class TestCLI:
    def _write(self, tmp_path):
        ng = tmp_path / "nodegroups.yaml"
        ng.write_text(NODEGROUPS_YAML)
        sim = tmp_path / "state.yaml"
        sim.write_text(SIM_STATE_YAML)
        return ng, sim

    def test_once_prints_deltas(self, tmp_path):
        ng, sim = self._write(tmp_path)
        from escalator_tpu.cli import main

        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = main([
                "--nodegroups", str(ng), "--sim-state", str(sim),
                "--backend", "golden", "--once",
            ])
        assert rc == 0
        out = json.loads(buf.getvalue())
        # 2000m req / 2000m cap = 100% -> delta ceil(2*(100-70)/70) = 1
        assert out["deltas"] == {"buildeng": 1}
        assert out["provider_targets"] == {"buildeng": 3}

    def test_invalid_config_fails_fast(self, tmp_path):
        ng = tmp_path / "bad.yaml"
        ng.write_text("node_groups:\n  - name: x\n")
        from escalator_tpu.cli import main

        with pytest.raises(SystemExit):
            main(["--nodegroups", str(ng), "--once"])

    def test_missing_cluster_source_errors(self, tmp_path):
        ng, _ = self._write(tmp_path)
        from escalator_tpu.cli import main

        with pytest.raises(SystemExit, match="no cluster source"):
            main(["--nodegroups", str(ng), "--once"])


class TestElectionCAS:
    def test_no_split_brain_on_empty_lock(self):
        """Strict CAS: with no record, exactly one of two racing candidates wins."""
        lock = InMemoryResourceLock()
        a_won = lock.create_or_update(LeaderRecord("a", 0, 0), None)
        b_won = lock.create_or_update(LeaderRecord("b", 0, 0), None)
        assert a_won and not b_won
        assert lock.get().holder == "a"

    def test_file_lock_cross_process_exclusion(self, tmp_path):
        """Two separate processes race to acquire the same empty file lease;
        exactly one must win (fcntl-serialized CAS)."""
        import subprocess, sys
        path = tmp_path / "lease.json"
        code = f"""
import sys
sys.path.insert(0, {str(__import__('pathlib').Path(__file__).parents[1])!r})
from escalator_tpu.k8s.election import FileResourceLock, LeaderRecord
lock = FileResourceLock({str(path)!r})
won = lock.create_or_update(LeaderRecord(sys.argv[1], 0, 0), None)
print(int(won))
"""
        procs = [
            subprocess.Popen([sys.executable, "-c", code, who],
                             stdout=subprocess.PIPE)
            for who in ("a", "b")
        ]
        results = [int(p.communicate()[0].strip()) for p in procs]
        assert sum(results) == 1

    def test_renew_retries_until_deadline(self):
        """A transiently failing lock does not depose before the renew deadline."""
        clock = MockClock()

        class FlakyLock(InMemoryResourceLock):
            fail = False

            def create_or_update(self, record, expected):
                if self.fail:
                    raise OSError("transient")
                return super().create_or_update(record, expected)

        lock = FlakyLock()
        deposed = threading.Event()
        e = LeaderElector(lock, FAST, identity="a", clock=clock,
                          on_deposed=deposed.set)
        assert e.run(blocking_acquire_timeout=1)
        lock.fail = True
        # two failed rounds (1.0s elapsed) < renew_deadline (3.0s): must NOT depose
        e._stop = FakeStopOnce(clock, FAST.retry_period_sec, rounds=2)
        e._renew_loop()
        assert not deposed.is_set()
        # eight more failed rounds (4.0s) > renew_deadline: must depose
        e._stop = FakeStopOnce(clock, FAST.retry_period_sec, rounds=8)
        e._renew_loop()
        assert deposed.is_set()
        assert not e.is_leader


class TestStaleLeaseTakeoverRace:
    """Round-11 crash-consistency satellite: a standby taking over an
    EXPIRED lease while the old leader's renew is still in flight (slow
    renewer: wrote its record read, stalled, writes late). The fcntl-guarded
    CAS must serialize the pair so exactly one outcome exists: the standby
    holds, and the stale renewal FAILS (then deposes its elector) — never a
    silently restored stale leader."""

    def test_slow_renewer_loses_to_takeover(self, tmp_path):
        lock = FileResourceLock(str(tmp_path / "lease.json"))
        clock = MockClock()
        # leader "a" held the lease but stopped renewing long ago
        assert lock.create_or_update(LeaderRecord("a", 0.0, 0.0), None)
        clock.advance(100)   # way past FAST.lease_duration_sec

        release = threading.Event()
        results = {}

        class SlowLock(FileResourceLock):
            """a's view of the lock: its renew stalls until released —
            modeling a renewer descheduled between deciding to renew and
            performing the guarded CAS."""

            def create_or_update(self, record, expected):
                release.wait(10)
                return super().create_or_update(record, expected)

        slow = SlowLock(lock.path)

        def renew_a():
            results["a"] = slow.create_or_update(
                LeaderRecord("a", clock.now(), clock.now()), "a")

        ta = threading.Thread(target=renew_a)
        ta.start()
        # standby b observes the expired lease and takes it over while a's
        # renewal is in flight
        b = LeaderElector(lock, FAST, identity="b", clock=clock)
        assert b._try_acquire()
        assert lock.get().holder == "b"
        release.set()
        ta.join(10)
        # a's late renewal must FAIL: the CAS re-reads under the guard and
        # sees holder=b, not the 'a' it expected
        assert results["a"] is False
        assert lock.get().holder == "b"
        # and a's renew loop, seeing the usurper, deposes immediately
        deposed = threading.Event()
        a = LeaderElector(lock, FAST, identity="a", clock=clock,
                          on_deposed=deposed.set)
        a.is_leader = True
        a._stop = FakeStopOnce(clock, FAST.retry_period_sec, rounds=2)
        a._renew_loop()
        assert deposed.is_set() and not a.is_leader
        b.stop()

    def test_crash_during_write_leaves_previous_lease_intact(
            self, tmp_path, monkeypatch):
        """Crash consistency: a writer dying mid-write (fsync fails — disk
        gone) must never leave a torn lease — the previous record stays
        readable (atomic rename never happened) and no tmp debris
        accumulates where a reader could trip on it."""
        from escalator_tpu.utils import atomicio

        lock = FileResourceLock(str(tmp_path / "lease.json"))
        assert lock.create_or_update(LeaderRecord("a", 1.0, 2.0), None)

        def boom(fd):
            raise OSError("disk gone")

        monkeypatch.setattr(atomicio.os, "fsync", boom)
        with pytest.raises(OSError, match="disk gone"):
            lock.create_or_update(LeaderRecord("a", 3.0, 3.0), "a")
        monkeypatch.undo()
        got = lock.get()
        assert got is not None and got.renew_time == 2.0   # old record intact
        debris = [p for p in tmp_path.iterdir() if ".tmp" in p.name]
        assert not debris


class FakeStopOnce:
    """Stop event that advances a mock clock per wait and stops after N rounds."""

    def __init__(self, clock, period, rounds):
        self.clock = clock
        self.period = period
        self.rounds = rounds

    def wait(self, timeout):
        if self.rounds <= 0:
            return True
        self.rounds -= 1
        self.clock.advance(self.period)
        return False

    def is_set(self):
        return self.rounds <= 0


class TestHealthEndpoints:
    """/healthz (liveness) and /readyz (readiness) on the metrics server —
    no reference analog (its mux serves /metrics only, metrics.go:260-268);
    the Deployment manifests' probes point here."""

    def _get(self, port, path):
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_healthz_readyz_states(self):
        from escalator_tpu.metrics import metrics as m

        state = {"ready": (False, "warming up")}
        server = m.start("127.0.0.1:0", readiness=lambda: state["ready"])
        try:
            port = server.server_address[1]
            assert self._get(port, "/healthz") == (200, "ok")
            code, body = self._get(port, "/readyz")
            assert code == 503 and "warming up" in body
            state["ready"] = (True, "ok (last tick 1s ago)")
            code, body = self._get(port, "/readyz")
            assert code == 200 and "last tick" in body
            # a crashing readiness callable reads as not-ready, not a 500
            state["ready"] = None  # unpackable -> TypeError inside route
            code, body = self._get(port, "/readyz")
            assert code == 503 and "readiness check failed" in body
            assert self._get(port, "/metrics")[0] == 200
            assert self._get(port, "/nope")[0] == 404
        finally:
            server.shutdown()

    def test_no_readiness_callable_is_ready(self):
        from escalator_tpu.metrics import metrics as m

        server = m.start("127.0.0.1:0")
        try:
            port = server.server_address[1]
            assert self._get(port, "/readyz") == (200, "ok")
        finally:
            server.shutdown()


class TestTickWatchdog:
    """A leader whose ticks stall must crash-to-restart (exit 70) so its
    Lease lapses and a standby promotes — readiness alone cannot fail over a
    controller that serves no traffic. No reference analog (its only
    self-termination paths are leader deposition and the fleet breaker)."""

    def test_stalled_ticks_exit_70(self, tmp_path):
        import os
        import subprocess
        import sys

        env = dict(os.environ)
        # limit deliberately below the scan interval: the first tick
        # completes immediately, then the idle gap trips the watchdog —
        # exercising the exit path without simulating a real wedge
        env["ESCALATOR_TPU_WATCHDOG_LIMIT_SEC"] = "3"
        proc = subprocess.run(
            [sys.executable, "-m", "escalator_tpu",
             "--nodegroups", "examples/nodegroups.yaml",
             "--sim-state", "examples/cluster-state.yaml",
             "--backend", "golden", "--scaninterval", "60s",
             "--address", "127.0.0.1:0"],
            env=env, capture_output=True, text=True, timeout=60,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 70, (proc.returncode, proc.stderr[-500:])
        assert "no tick completed" in proc.stderr

    def test_healthy_ticks_do_not_exit(self, tmp_path):
        import os
        import signal as sig
        import subprocess
        import sys
        import time as t

        env = dict(os.environ)
        env["ESCALATOR_TPU_WATCHDOG_LIMIT_SEC"] = "30"
        proc = subprocess.Popen(
            [sys.executable, "-m", "escalator_tpu",
             "--nodegroups", "examples/nodegroups.yaml",
             "--sim-state", "examples/cluster-state.yaml",
             "--backend", "golden", "--scaninterval", "1s",
             "--address", "127.0.0.1:0"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        try:
            t.sleep(10)  # several ticks; watchdog checks at limit/4 = 7.5s
            assert proc.poll() is None, proc.stderr.read().decode()[-500:]
        finally:
            proc.send_signal(sig.SIGTERM)
            proc.wait(timeout=30)


class TestCliBackendMatrix:
    """Every CLI backend must print the SAME deltas for the same world — the
    cross-backend consistency the verify recipe drives by hand, locked at the
    CLI wiring layer (backend construction, probe guards, result assembly).
    All in-process: jax is already initialized on cpu here, so the
    wedged-transport probe fast-paths to a no-op."""

    def _run(self, configs, backend, extra=()):
        import io
        from contextlib import redirect_stdout

        from escalator_tpu.cli import main

        ng, sim = configs
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = main([
                "--nodegroups", str(ng), "--sim-state", str(sim),
                "--backend", backend, "--once", *extra,
            ])
        assert rc == 0
        return json.loads(buf.getvalue())

    @pytest.fixture
    def configs(self, tmp_path):
        ng = tmp_path / "nodegroups.yaml"
        ng.write_text(NODEGROUPS_YAML)
        sim = tmp_path / "state.yaml"
        sim.write_text(SIM_STATE_YAML)
        return ng, sim

    def test_fleet_example_all_backends_agree(self):
        """The shipped 4-group fleet example: each group in a different
        regime (scale-up / no-op / fast scale-down / scale-from-pending),
        identical across backends — the README quickstart claim, locked."""
        import pathlib

        repo = pathlib.Path(__file__).resolve().parent.parent
        configs = (repo / "examples" / "nodegroups-fleet.yaml",
                   repo / "examples" / "cluster-state-fleet.yaml")
        want = self._run(configs, "golden")
        assert want["deltas"] == {
            "buildeng": 1, "dataeng": 0, "ci": -10, "batch": 3}
        for backend in ("jax", "sharded-jax", "grid-jax", "podaxis-jax",
                        "native"):
            got = self._run(configs, backend)
            assert got == want, f"{backend} disagrees on the fleet example"

    def test_all_backends_agree(self, configs):
        want = self._run(configs, "golden")
        assert want["deltas"] == {"buildeng": 1}
        for backend in ("jax", "native", "podaxis-jax", "grid-jax"):
            got = self._run(configs, backend)
            assert got == want, f"{backend} disagrees with golden"

    def test_grpc_backend_agrees(self, configs, caplog):
        import logging

        from escalator_tpu.plugin.server import make_server

        server = make_server("127.0.0.1:0", max_workers=2)
        try:
            server.start()
            port = server._escalator_bound_port
            with caplog.at_level(logging.WARNING, logger="escalator_tpu.plugin"):
                got = self._run(configs, "grpc",
                                extra=("--plugin-address", f"127.0.0.1:{port}"))
        finally:
            server.stop(grace=None)
        # GrpcBackend silently degrades to the golden backend on RpcError, so
        # agreement alone would be vacuous — the RPC path must actually have
        # answered (no fallback warning fired)
        assert not any("compute plugin unavailable" in r.message
                       for r in caplog.records), caplog.text
        assert got == self._run(configs, "golden")
