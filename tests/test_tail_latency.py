"""Tail-latency layer (round 13): streaming histograms, slow-tick deep
capture, Perfetto trace export.

Locks the ISSUE-8 contracts:

- **histograms**: record/merge/quantile correctness within one bucket width
  of ``np.percentile`` ground truth on adversarial distributions (bimodal,
  heavy tail, single sample), exact bucket-boundary placement, under/overflow
  clamping, counter-exact merges;
- **tail capture**: a root tick breaching ``multiplier x`` the live rolling
  p99 triggers a ``reason="tail"`` flight dump (worker-thread, rate-limited)
  whose document carries the breach annotation and the breaching tick's
  span tree; env parsing is validated;
- **trace export**: any flight dump renders to schema-valid Chrome
  trace-event / Perfetto JSON — nested phases as X duration events, unfenced
  overlap dispatches and grafted plugin-server spans on their own tracks —
  and a REAL plugin-routed decide produces one merged client+server trace
  through the actual ``escalator-tpu debug-trace`` verb;
- **inertness**: with tail capture armed and histograms streaming, traced
  entries' jaxprs stay byte-identical to the recording-off arm (the layer
  hangs off the timeline-completion hook, strictly outside traced code);
- **plugin health**: ``tick_p99_ms``/``tick_p999_ms`` ride the health
  response, so a stale-but-alive server's tail is visible without a
  Prometheus scrape.
"""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

from escalator_tpu import observability as obs
from escalator_tpu.metrics import metrics
from escalator_tpu.observability import histograms as hg
from escalator_tpu.observability import spans, tail, traceexport


def _counter(name, labels=None):
    return metrics.registry.get_sample_value(name, labels or {}) or 0.0


# ----------------------------------------------------------- histogram engine
DISTRIBUTIONS = {
    "bimodal": lambda rng: np.concatenate([
        rng.normal(2e-3, 3e-4, 5000), rng.normal(8e-2, 1e-2, 300)]),
    "heavy_tail": lambda rng: (rng.pareto(1.5, 5000) + 1) * 1e-4,
    "lognormal": lambda rng: rng.lognormal(-6.0, 1.5, 4000),
    "single_sample": lambda rng: np.array([1.23e-2]),
}


@pytest.mark.parametrize("dist", sorted(DISTRIBUTIONS))
def test_quantiles_within_one_bucket_of_percentile(dist):
    rng = np.random.default_rng(17)
    samples = np.clip(DISTRIBUTIONS[dist](rng), 1e-7, 9.0)
    h = hg.LogHistogram()
    for s in samples:
        h.record(float(s))
    assert h.count == len(samples)
    assert h.sum_seconds == pytest.approx(float(samples.sum()), rel=1e-9)
    assert h.max_seconds == pytest.approx(float(samples.max()))
    assert h.min_seconds == pytest.approx(float(samples.min()))
    for q in (0.0, 0.5, 0.9, 0.99, 0.999, 1.0):
        gt = float(np.percentile(samples, q * 100))
        got = h.quantile(q)
        lo, hi = hg.bucket_bounds(gt)
        assert abs(got - gt) <= (hi - lo) + 1e-12, (
            f"{dist} p{q * 100:g}: {got} vs ground truth {gt} "
            f"(bucket width {hi - lo})")


def test_bucket_boundary_exactness_and_clamping():
    # an exact edge value belongs to the bucket it OPENS: [edge_i, edge_i+1)
    for i in (0, 1, 7, 36, hg.NUM_BUCKETS - 1):
        assert hg.bucket_index(hg.EDGES[i]) == i + 1, i
        # one ulp below the edge stays in the previous bucket (i=0 underflows)
        below = np.nextafter(hg.EDGES[i], 0.0)
        assert hg.bucket_index(float(below)) == i, i
    # range clamps: underflow and overflow have their own slots
    assert hg.bucket_index(0.0) == 0
    assert hg.bucket_index(5e-7) == 0
    assert hg.bucket_index(hg.HI) == hg.NUM_BUCKETS + 1
    assert hg.bucket_index(123.0) == hg.NUM_BUCKETS + 1
    h = hg.LogHistogram()
    h.record(0.0)
    h.record(99.0)
    assert h.count == 2
    assert h.quantile(0.0) == hg.LO / 2      # underflow reported inside (0, LO)
    assert h.quantile(1.0) == hg.HI          # overflow clamps to HI
    # consecutive bucket bounds grow by exactly BASE (the 25% error bound)
    lo1, hi1 = hg.bucket_bounds(1e-3)
    assert hi1 / lo1 == pytest.approx(hg.BASE)


def test_merge_is_counter_exact():
    rng = np.random.default_rng(3)
    s1 = rng.lognormal(-6, 1, 2000)
    s2 = rng.lognormal(-3, 0.5, 500)
    a, b, whole = hg.LogHistogram(), hg.LogHistogram(), hg.LogHistogram()
    for s in s1:
        a.record(float(s))
        whole.record(float(s))
    for s in s2:
        b.record(float(s))
        whole.record(float(s))
    a.merge(b)
    assert a.count == whole.count
    assert a.sum_seconds == pytest.approx(whole.sum_seconds)
    assert list(a._counts) == list(whole._counts)
    for q in (0.5, 0.99, 0.999):
        assert a.quantile(q) == whole.quantile(q)
    # empty histogram: quantiles are None, not garbage
    assert hg.LogHistogram().quantile(0.99) is None
    assert hg.LogHistogram().quantiles()["p999"] is None


def test_hook_feeds_phase_and_tick_histograms():
    """Completed timelines land leaf phases in PHASES (composites and
    grafted remote phases excluded — the Prometheus selection) and the root
    duration in TICKS keyed by root name."""
    root = "histfeed_root"
    with spans.span(root):
        spans.annotate(backend="histfeed")
        with spans.span("outer"):
            with spans.span("inner"):
                pass
        spans.graft([{"name": "srv", "path": "remote/srv", "ms": 1.0}],
                    under=f"{root}/outer")
    assert hg.PHASES.peek("histfeed", "inner").count >= 1
    assert hg.PHASES.peek("histfeed", "outer") is None      # composite
    assert hg.PHASES.peek("histfeed", "srv") is None        # remote
    tick_h = hg.TICKS.peek(root)
    assert tick_h is not None and tick_h.count == 1
    q = hg.tick_quantiles_ms(root)
    assert q["count"] == 1 and q["p99"] is not None
    # the merged process view (plugin health's source) includes this root
    assert hg.tick_quantiles_ms()["count"] >= 1


def test_prometheus_export_carries_fine_histograms():
    with spans.span("promfeed_tick"):
        spans.annotate(backend="promfeed")
        with spans.span("work"):
            time.sleep(0.001)
    from prometheus_client import generate_latest

    text = generate_latest(metrics.registry).decode()
    assert 'escalator_tpu_tick_phase_hist_seconds_bucket{' in text
    assert 'escalator_tpu_tick_e2e_seconds_bucket{' in text
    assert 'root="promfeed_tick"' in text
    # cumulative counts end at +Inf == count
    assert _counter("escalator_tpu_tick_e2e_seconds_count",
                    {"root": "promfeed_tick"}) >= 1


def test_cumulative_buckets_expose_identical_le_sets():
    """`sum by (le)` quantile queries (the shipped Grafana panels) require
    every series to emit the SAME full `le` set: a series truncated at its
    own last non-empty bucket sums non-monotonically and histogram_quantile
    returns garbage. Two histograms at very different magnitudes must expose
    identical bucket labels, and each series must be monotone."""
    fast, slow = hg.LogHistogram(), hg.LogHistogram()
    for _ in range(100):
        fast.record(2e-4)
        slow.record(1.2e-2)
    fb, sb = fast.cumulative_buckets(), slow.cumulative_buckets()
    assert [le for le, _ in fb] == [le for le, _ in sb]
    assert len(fb) == hg.NUM_BUCKETS + 1 and fb[-1][0] == "+Inf"
    for series in (fb, sb):
        counts = [c for _, c in series]
        assert counts == sorted(counts) and counts[-1] == 100
    # the cross-series sum stays monotone in le (what sum by (le) scrapes)
    summed = [a + b for (_, a), (_, b) in zip(fb, sb)]
    assert summed == sorted(summed)


# ------------------------------------------------------------- tail capture
def test_parse_tail_capture_spellings():
    assert tail.parse_tail_capture(None) == tail.DEFAULT_MULTIPLIER
    assert tail.parse_tail_capture("") == tail.DEFAULT_MULTIPLIER
    assert tail.parse_tail_capture("2.5") == 2.5
    for off in ("off", "0", "OFF", "false", "-1", "none"):
        assert tail.parse_tail_capture(off) is None, off
    assert tail.parse_tail_capture("bogus") is None   # warn, never crash


def _run_ticks(root, n, sleep_sec, leaf="steady_work"):
    for _ in range(n):
        with spans.span(root):
            spans.annotate(backend="tailtest")
            with spans.span(leaf):
                time.sleep(sleep_sec)


def test_tail_breach_dumps_and_rate_limits(tmp_path, monkeypatch):
    root = "tailtest_breach_tick"
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_CAPTURE", "3.0")
    # min_ticks == seed count: the watchdog arms exactly at the slow tick.
    # 100 seeds (not 10) so ONE outlier can't drag the rolling p99 into the
    # slow bucket — the rate-limit leg below needs the SECOND slow tick to
    # still register as a breach.
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_MIN_TICKS", "100")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_DUMP_INTERVAL_SEC", "600")
    tail.WATCHDOG.reset()
    before = _counter("escalator_tpu_flight_recorder_dumps_total",
                      {"reason": "tail"})
    _run_ticks(root, 100, 0.0005)
    _run_ticks(root, 1, 0.05, leaf="slow_work")
    tail.WATCHDOG.drain()
    dumps = sorted(tmp_path.glob("escalator-tpu-flight-tail-*.json"))
    assert len(dumps) == 1, dumps
    doc = json.loads(dumps[0].read_text())
    assert doc["reason"] == "tail" and doc["flight_recorder"]
    breach = doc["tail"]
    assert breach["root"] == root
    assert breach["duration_ms"] > breach["threshold_ms"]
    assert breach["threshold_ms"] == pytest.approx(
        3.0 * breach["p99_ms"], abs=2e-3)   # both rounded to 4 decimals
    assert breach["tick_count"] >= 100
    # the bundle is self-contained forensics: the breaching tick's span
    # tree is in the shipped ring, and the live tail quantiles ride along
    assert any(r.get("seq") == breach["seq"]
               and any(p["name"] == "slow_work" for p in r["phases"])
               for r in doc["ticks"])
    assert doc["tick_quantiles_ms"]["count"] > 0
    assert _counter("escalator_tpu_flight_recorder_dumps_total",
                    {"reason": "tail"}) == before + 1
    # rate limit: an immediate second breach records but does not dump
    _run_ticks(root, 1, 0.05, leaf="slow_work")
    tail.WATCHDOG.drain()
    assert len(list(tmp_path.glob("escalator-tpu-flight-tail-*.json"))) == 1
    assert tail.WATCHDOG.breaches >= 2 and tail.WATCHDOG.dumps == 1
    tail.WATCHDOG.reset()


def test_tail_p99_cache_invalidated_by_series_replacement(tmp_path,
                                                          monkeypatch):
    """histograms.reset() restarts every series at count 0; a p99 cached
    against the dead population must not be served to the fresh one (the
    cache guards on count going backwards)."""
    root = "tailtest_cachereset_tick"
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_CAPTURE", "3.0")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_MIN_TICKS", "10")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_DUMP_INTERVAL_SEC", "600")
    tail.WATCHDOG.reset()
    # population A: SLOW ticks — caches a large p99 (threshold ~120 ms)
    _run_ticks(root, 11, 0.04)
    assert tail.WATCHDOG.breaches == 0
    # series replaced: population B is ~40x faster; a stale 40 ms p99 would
    # hide the 50 ms breach below (3 x 40 ms >> 50 ms). The wide gaps —
    # 1 ms seeds, 50 ms probe, 120 ms stale threshold — keep suite
    # contention (a 1 ms sleep stretching several-fold on a stalled core)
    # from flipping either leg.
    hg.TICKS.clear()
    _run_ticks(root, 10, 0.001)
    _run_ticks(root, 1, 0.05, leaf="slow_work")
    tail.WATCHDOG.drain()
    assert tail.WATCHDOG.breaches >= 1, (
        "stale p99 from the replaced series suppressed the breach")
    tail.WATCHDOG.reset()


def test_tail_capture_off_never_dumps(tmp_path, monkeypatch):
    root = "tailtest_off_tick"
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_CAPTURE", "off")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_MIN_TICKS", "5")
    tail.WATCHDOG.reset()
    _run_ticks(root, 5, 0.001)
    _run_ticks(root, 1, 0.05, leaf="slow_work")
    tail.WATCHDOG.drain()
    assert not list(tmp_path.glob("escalator-tpu-flight-tail-*.json"))
    # the histograms keep streaming even with capture off
    assert hg.TICKS.peek(root).count == 6
    tail.WATCHDOG.reset()


# -------------------------------------------------------------- trace export
def _validate_trace_events(doc):
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for e in doc["traceEvents"]:
        assert {"name", "ph", "pid", "tid"} <= set(e), e
        assert e["ph"] in ("X", "M"), e
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)), e
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0, e
            assert isinstance(e["args"]["path"], str)
    return [e for e in doc["traceEvents"] if e["ph"] == "X"]


def test_trace_export_nesting_and_tracks():
    root = "tracetest_tick"
    with spans.span(root):
        spans.annotate(backend="tracetest", digest="abc123")
        with spans.span("pack"):
            time.sleep(0.001)
        with spans.span("decide", kind="device"):
            spans.fence(None)
            time.sleep(0.002)
        with spans.span("overlapped", kind="device"):
            pass   # never fenced: overlap track
    rec = obs.RECORDER.last()
    assert rec["root"] == root
    doc = traceexport.trace_from_records([rec])
    xs = _validate_trace_events(doc)
    by_name = {e["name"]: e for e in xs}
    root_ev = by_name[root]
    # containment: children inside the root slice, on the main track
    for child in ("pack", "decide"):
        e = by_name[child]
        assert e["tid"] == traceexport.TID_TICK
        assert root_ev["ts"] - 1 <= e["ts"]
        assert (e["ts"] + e["dur"]) <= root_ev["ts"] + root_ev["dur"] + 1
    # the unfenced device dispatch sits on the overlap track
    assert by_name["overlapped"]["tid"] == traceexport.TID_OVERLAP
    assert by_name["overlapped"]["args"]["fenced"] is False
    # root slice carries the record annotations
    assert root_ev["args"]["digest"] == "abc123"
    assert root_ev["args"]["backend"] == "tracetest"
    # metadata names the tracks
    meta = {(e["name"], e["tid"]): e for e in doc["traceEvents"]
            if e["ph"] == "M"}
    assert ("process_name", 0) in meta
    assert ("thread_name", traceexport.TID_OVERLAP) in meta


def test_trace_export_merges_client_and_server(tmp_path):
    """A REAL plugin-routed decide through an in-process gRPC server, dumped
    and rendered via the actual `escalator-tpu debug-trace` verb: one trace
    carries the client's rpc span and the grafted server-side decide on the
    plugin track, laid out inside the rpc window."""
    grpc = pytest.importorskip("grpc")  # noqa: F841 - availability gate
    from escalator_tpu.plugin.client import ComputeClient
    from escalator_tpu.plugin.server import make_server
    from tests.test_kernel_parity import random_group
    import random

    from escalator_tpu.core.arrays import pack_cluster

    cluster = pack_cluster([random_group(random.Random(2), 0)],
                           pad_pods=64, pad_nodes=16, pad_groups=2)
    server = make_server("127.0.0.1:0", max_workers=2)
    server.start()
    client = ComputeClient(f"127.0.0.1:{server._escalator_bound_port}",
                           timeout_sec=120.0)
    root = "tracetest_plugin_tick"
    try:
        with spans.span(root):
            spans.annotate(backend="grpc")
            with spans.span("rpc", kind="rpc"):
                _out, server_phases = client.decide_arrays_traced(
                    cluster, 1_700_000_000,
                    span_ctx={"path": spans.current_path()})
            assert server_phases, "server shipped no span sidecar"
            spans.graft(server_phases, under=f"{root}/rpc")
    finally:
        client.close()
        server.stop(grace=None)
    dump_path = tmp_path / "plugin-dump.json"
    obs.RECORDER.dump(str(dump_path), reason="test")
    out_path = tmp_path / "plugin.trace.json"
    from escalator_tpu.cli import main as cli_main

    rc = cli_main(["debug-trace", "--dump", str(dump_path),
                   "--output", str(out_path)])
    assert rc == 0
    doc = json.loads(out_path.read_text())
    xs = _validate_trace_events(doc)
    tick = [e for e in xs if e["args"]["path"].startswith(root)]
    rpc = next(e for e in tick if e["name"] == "rpc"
               and not e["args"].get("remote"))
    remote = [e for e in tick if e["args"].get("remote")]
    assert any(e["name"] == "decide" for e in remote), remote
    for e in remote:
        assert e["tid"] == traceexport.TID_REMOTE
        # re-anchored under the local rpc span (offsets are remote-root-
        # relative; the exporter lays them out from the rpc start)
        assert e["ts"] >= rpc["ts"] - 1, (e, rpc)


def test_debug_trace_unreadable_dump_exits_2(tmp_path, capsys):
    from escalator_tpu.cli import main as cli_main

    assert cli_main(["debug-trace", "--dump",
                     str(tmp_path / "missing.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{truncated")
    assert cli_main(["debug-trace", "--dump", str(bad)]) == 2


# ----------------------------------------------------------------- inertness
def test_jaxprs_byte_identical_with_tail_layer_armed(monkeypatch):
    """The tail layer hangs entirely off the timeline-completion hook:
    tracing with histograms streaming + tail capture armed yields jaxprs
    byte-identical to the recording-off arm."""
    import jax

    from escalator_tpu.analysis.registry import default_registry

    monkeypatch.setenv("ESCALATOR_TPU_TAIL_CAPTURE", "2.0")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_MIN_TICKS", "1")
    entry = {e.name: e for e in default_registry()}["kernel.delta_decide"]
    traced = entry.build()

    def jaxpr_text():
        return str(jax.make_jaxpr(traced.fn)(*traced.args))

    spans.set_enabled(False)
    try:
        plain = jaxpr_text()
    finally:
        spans.set_enabled(True)
    with spans.span("tail_inertness_trace"):
        instrumented = jaxpr_text()
    assert instrumented == plain


# ---------------------------------------------------------------- plugin tail
def test_plugin_health_carries_tail_fields():
    pytest.importorskip("grpc")
    import msgpack

    from escalator_tpu.plugin.server import _ComputeService

    svc = _ComputeService()
    # ensure at least one root tick exists in this process
    with spans.span("healthtest_tick"):
        pass
    h = msgpack.unpackb(svc.health(b"", None))
    assert "tick_p99_ms" in h and "tick_p999_ms" in h
    assert h["tick_p99_ms"] is None or h["tick_p99_ms"] > 0
    # the merged root view has ticks in this process, so the quantiles are
    # real numbers here (a fresh process would report None until a tick)
    assert hg.tick_quantiles_ms()["count"] > 0
    assert h["tick_p99_ms"] is not None


# --------------------------------------------------- concurrency (round 15)
def test_histogram_concurrent_record_merge_quantile_exact():
    """LogHistogram.record is called from tick, tail-dump-worker and fleet
    scheduler threads while scrape/health threads run merge/quantile — the
    counters must stay EXACT under that interleaving (a lost increment
    would silently skew every published quantile). Four writer threads
    hammer distinct duration ranges while a reader merges and queries
    concurrently; afterwards count, per-bucket totals and sum must equal
    the single-threaded truth."""
    import threading

    h = hg.LogHistogram()
    per_thread = 4000
    ranges = [(1e-5, 1e-4), (1e-3, 5e-3), (0.05, 0.2), (1.0, 4.0)]
    samples = []
    rng = np.random.default_rng(77)
    for lo, hi in ranges:
        samples.append(rng.uniform(lo, hi, per_thread))

    stop = threading.Event()
    reader_errors = []

    def reader():
        # concurrent merge + quantile must never crash or observe torn
        # state (count ahead of buckets, negative interpolation, ...)
        while not stop.is_set():
            try:
                m = hg.LogHistogram()
                m.merge(h)
                # the +Inf cumulative count is the series total
                assert m.cumulative_buckets()[-1][1] == m.count
                q = m.quantile(0.99)
                assert q is None or q > 0
            except Exception as e:  # noqa: BLE001
                reader_errors.append(e)
                return

    def writer(vals):
        for v in vals:
            h.record(float(v))

    readers = [threading.Thread(target=reader) for _ in range(2)]
    writers = [threading.Thread(target=writer, args=(vals,))
               for vals in samples]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not reader_errors, reader_errors

    # exactness: counts, bucket totals and sum match a serial reference
    ref = hg.LogHistogram()
    for vals in samples:
        for v in vals:
            ref.record(float(v))
    assert h.count == len(ranges) * per_thread == ref.count
    assert h.cumulative_buckets() == ref.cumulative_buckets()
    assert h.sum_seconds == pytest.approx(ref.sum_seconds, rel=1e-9)
    for q in (0.5, 0.99, 0.999):
        assert h.quantile(q) == ref.quantile(q)
