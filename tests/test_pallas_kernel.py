"""Pallas fused-aggregation kernel: exact parity against the XLA scatter path.

The Pallas sweep (ops/pallas_kernel.py) must be bit-identical to
``jax.ops.segment_sum`` — it feeds the same decision math the golden parity
suite certifies. Runs in interpret mode on the CPU test backend (conftest
pins JAX_PLATFORMS=cpu); on a real TPU the identical traced program compiles
through Mosaic.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from escalator_tpu.ops import pallas_kernel as pk  # noqa: E402
from escalator_tpu.ops import kernel  # noqa: E402


def _ref_sums(ids, valid, int_cols, cnt_cols, G):
    out = {}
    for name, col in {**int_cols, **cnt_cols}.items():
        out[name] = np.zeros(G, np.int64)
        np.add.at(out[name], ids, col.astype(np.int64))
    return out


def _sorted_ids(rng, P, G):
    """Group-contiguous ids, the packer's layout (some groups empty)."""
    counts = rng.multinomial(P, np.ones(G) / G)
    return np.repeat(np.arange(G, dtype=np.int32), counts)


@pytest.mark.parametrize("P,G", [(1, 1), (100, 4), (1333, 7), (5000, 300)])
def test_fused_sums_match_reference_sorted(P, G):
    rng = np.random.default_rng(P * 31 + G)
    ids = _sorted_ids(rng, P, G)
    valid = rng.random(P) < 0.9
    cpu = rng.integers(0, 2**40, P).astype(np.int64) * valid
    mem = rng.integers(0, 2**47, P).astype(np.int64) * valid
    cnt = valid.copy()

    got = pk.fused_segment_sums(
        jnp.asarray(ids),
        jnp.asarray(valid),
        {"cpu": jnp.asarray(cpu), "mem": jnp.asarray(mem)},
        {"cnt": jnp.asarray(cnt)},
        num_segments=G,
        interpret=True,
    )
    want = _ref_sums(ids, valid, {"cpu": cpu, "mem": mem}, {"cnt": cnt}, G)
    for name in want:
        np.testing.assert_array_equal(np.asarray(got[name]), want[name], err_msg=name)


def test_fused_sums_sorts_unsorted_layout_on_device():
    """Scattered ids break the direct window precondition; the kernel now
    restores contiguity with an on-device argsort and still rides the MXU
    (this is the incremental-store slot-reuse layout, ops/device_state.py)."""
    rng = np.random.default_rng(7)
    P, G = 4000, 1024
    ids = rng.integers(0, G, P).astype(np.int32)  # random => huge per-tile spread
    valid = np.ones(P, bool)
    cpu = rng.integers(0, 2**40, P).astype(np.int64)

    report = pk.path_report(ids, valid, {"cpu": cpu})
    assert report["path"] == "pallas-sorted"
    assert not report["direct_ok"] and report["sorted_ok"]

    got = pk.fused_segment_sums(
        jnp.asarray(ids),
        jnp.asarray(valid),
        {"cpu": jnp.asarray(cpu)},
        {},
        num_segments=G,
        interpret=True,
    )
    want = _ref_sums(ids, valid, {"cpu": cpu}, {}, G)
    np.testing.assert_array_equal(np.asarray(got["cpu"]), want["cpu"])


def test_fused_sums_slot_reuse_interleaving_takes_sorted_mxu_path():
    """The exact churn pattern that used to exile cfg6 to the scatter path:
    group-contiguous base layout with a fraction of freed slots reused by
    OTHER groups. Must take the sorted MXU path and stay bit-exact, including
    invalid (freed) lanes and partially-filled tails."""
    rng = np.random.default_rng(11)
    # G must exceed the kernel's WINDOW: with few groups any interleaving still
    # fits one tile window and the direct path absorbs it
    P, G = 12000, 2048
    ids = _sorted_ids(rng, P, G)
    valid = np.ones(P, bool)
    # churn: 15% of slots freed, half of those reused by random other groups
    freed = rng.random(P) < 0.15
    valid[freed] = False
    reused = freed & (rng.random(P) < 0.5)
    ids[reused] = rng.integers(0, G, int(reused.sum())).astype(np.int32)
    valid[reused] = True
    cpu = rng.integers(0, 2**40, P).astype(np.int64) * valid
    mem = rng.integers(0, 2**47, P).astype(np.int64) * valid
    cnt = valid.copy()

    report = pk.path_report(ids, valid, {"cpu": cpu, "mem": mem})
    assert report["path"] == "pallas-sorted"

    got = pk.fused_segment_sums(
        jnp.asarray(ids),
        jnp.asarray(valid),
        {"cpu": jnp.asarray(cpu), "mem": jnp.asarray(mem)},
        {"cnt": jnp.asarray(cnt)},
        num_segments=G,
        interpret=True,
    )
    want = _ref_sums(
        ids[valid], np.ones(int(valid.sum()), bool),
        {"cpu": cpu[valid], "mem": mem[valid]}, {"cnt": cnt[valid]}, G,
    )
    for name in want:
        np.testing.assert_array_equal(np.asarray(got[name]), want[name], err_msg=name)


def test_fused_sums_tiny_group_pathology_falls_back_to_scatter():
    """Under ~1 lane per group even a sorted tile spans > MAX_SPREAD distinct
    groups — the one layout where scatter genuinely is the right tool."""
    rng = np.random.default_rng(13)
    G = 4096
    P = G  # one lane per group
    ids = rng.permutation(G).astype(np.int32)
    valid = np.ones(P, bool)
    cpu = rng.integers(0, 2**40, P).astype(np.int64)

    report = pk.path_report(ids, valid, {"cpu": cpu})
    assert report["path"] == "xla-scatter"
    assert not report["sorted_ok"]

    got = pk.fused_segment_sums(
        jnp.asarray(ids), jnp.asarray(valid), {"cpu": jnp.asarray(cpu)}, {},
        num_segments=G, interpret=True,
    )
    want = _ref_sums(ids, valid, {"cpu": cpu}, {}, G)
    np.testing.assert_array_equal(np.asarray(got["cpu"]), want["cpu"])


def test_fused_sums_fallback_on_out_of_range_values():
    """Values >= 2^48 exceed the limb range -> XLA branch, still exact."""
    ids = np.zeros(600, np.int32)
    valid = np.ones(600, bool)
    big = np.full(600, 2**50, np.int64)  # >= 2^48 but the sum still fits int64
    got = pk.fused_segment_sums(
        jnp.asarray(ids), jnp.asarray(valid), {"v": jnp.asarray(big)}, {},
        num_segments=4, interpret=True,
    )
    assert int(got["v"][0]) == 600 * 2**50


def test_fused_sums_empty_groups_between_populated():
    """Empty groups inflate the window spread; either path must stay exact."""
    P = 1000
    ids = np.concatenate(
        [np.zeros(P // 2, np.int32), np.full(P - P // 2, 1900, np.int32)]
    )
    valid = np.ones(P, bool)
    cpu = np.full(P, 12345, np.int64)
    got = pk.fused_segment_sums(
        jnp.asarray(ids), jnp.asarray(valid), {"cpu": jnp.asarray(cpu)}, {},
        num_segments=2048, interpret=True,
    )
    want = _ref_sums(ids, valid, {"cpu": cpu}, {}, 2048)
    np.testing.assert_array_equal(np.asarray(got["cpu"]), want["cpu"])


def test_native_store_churned_layout_reaches_mxu_path():
    """cfg6's blocker, lifted: a native store whose freelist recycles slots
    across groups used to exile the event-driven tick to the scatter path
    forever. Assert the live store columns now route to the sorted MXU path."""
    from escalator_tpu.native import statestore

    if not statestore.available():
        pytest.skip("native statestore unavailable")
    rng = np.random.default_rng(17)
    G, per_group = 2048, 8
    store = statestore.NativeStateStore(
        pod_capacity=1 << 15, node_capacity=64
    )
    uid = 0
    for g in range(G):
        for _ in range(per_group):
            store.upsert_pod(f"p{uid}", g, 100, 1 << 20)
            uid += 1
    # churn: delete a random 10%, re-add as pods of random OTHER groups —
    # the freelist hands their slots to the new pods, interleaving groups
    victims = rng.choice(uid, size=uid // 10, replace=False)
    for v in victims:
        store.delete_pod(f"p{v}")
    for i, _ in enumerate(victims):
        store.upsert_pod(f"q{i}", int(rng.integers(0, G)), 100, 1 << 20)
    pods, _ = store.as_pod_node_arrays()
    cpu = pods.cpu_milli * pods.valid
    report = pk.path_report(pods.group, pods.valid, {"cpu": cpu})
    assert report["path"] == "pallas-sorted", report
    # and the sums are still exact through the kernel
    got = pk.fused_segment_sums(
        jnp.asarray(np.where(pods.valid, pods.group, 0)),
        jnp.asarray(np.asarray(pods.valid)),
        {"cpu": jnp.asarray(np.asarray(cpu))},
        {},
        num_segments=G,
        interpret=True,
    )
    want = np.zeros(G, np.int64)
    np.add.at(want, pods.group[pods.valid], cpu[pods.valid])
    np.testing.assert_array_equal(np.asarray(got["cpu"]), want)


@pytest.mark.parametrize("layout", ["packed", "interleaved"])
def test_decide_pallas_impl_matches_xla_impl(layout):
    """Full decision kernel: impl='pallas' is bit-identical to impl='xla',
    on both the packer's group-contiguous layout and the incremental store's
    slot-reused interleaving."""
    from escalator_tpu.core.arrays import ClusterArrays, GroupArrays, NodeArrays, PodArrays
    from escalator_tpu.core.arrays import NO_TAINT_TIME

    rng = np.random.default_rng(3)
    if layout == "packed":
        G, P, N = 64, 3000, 900
        pod_group = _sorted_ids(rng, P, G)
        node_group = _sorted_ids(rng, N, G)
    else:
        # G > WINDOW so interleaving really breaks the direct layout; enough
        # lanes per group that the pod sweep takes the sorted MXU path (the
        # sparser node sweep falls to scatter — mixed paths in one decide)
        G, P, N = 1024, 8000, 1200
        pod_group = rng.integers(0, G, P).astype(np.int32)
        node_group = rng.integers(0, G, N).astype(np.int32)
        assert pk.path_report(pod_group, np.ones(P, bool))["path"] == "pallas-sorted"
    tainted = rng.random(N) < 0.3
    cluster = ClusterArrays(
        groups=GroupArrays(
            min_nodes=np.zeros(G, np.int32),
            max_nodes=np.full(G, 10**6, np.int32),
            taint_lower=np.full(G, 30, np.int32),
            taint_upper=np.full(G, 45, np.int32),
            scale_up_thr=np.full(G, 70, np.int32),
            slow_rate=np.ones(G, np.int32),
            fast_rate=np.full(G, 2, np.int32),
            locked=rng.random(G) < 0.1,
            requested_nodes=rng.integers(0, 5, G).astype(np.int32),
            cached_cpu_milli=np.full(G, 4000, np.int64),
            cached_mem_bytes=np.full(G, 16 * 10**9, np.int64),
            soft_grace_sec=np.full(G, 300, np.int64),
            hard_grace_sec=np.full(G, 900, np.int64),
            emptiest=np.zeros(G, bool),
            valid=np.ones(G, bool),
        ),
        pods=PodArrays(
            group=pod_group,
            cpu_milli=rng.integers(0, 16000, P).astype(np.int64),
            mem_bytes=rng.integers(0, 64 * 10**9, P).astype(np.int64),
            node=rng.integers(-1, N, P).astype(np.int32),
            valid=rng.random(P) < 0.95,
        ),
        nodes=NodeArrays(
            group=node_group,
            cpu_milli=np.full(N, 4000, np.int64),
            mem_bytes=np.full(N, 16 * 10**9, np.int64),
            creation_ns=rng.integers(1, 10**15, N).astype(np.int64),
            tainted=tainted,
            cordoned=(~tainted) & (rng.random(N) < 0.05),
            no_delete=rng.random(N) < 0.02,
            taint_time_sec=np.where(
                tainted, 1_700_000_000 - rng.integers(0, 2000, N), NO_TAINT_TIME
            ).astype(np.int64),
            valid=rng.random(N) < 0.97,
        ),
    )
    now = np.int64(1_700_000_000)
    a = kernel.decide_jit(cluster, now, impl="xla")
    b = kernel.decide_jit(cluster, now, impl="pallas")
    for f in (
        "status nodes_delta cpu_percent mem_percent cpu_request_milli "
        "mem_request_bytes cpu_capacity_milli mem_capacity_bytes num_pods "
        "num_nodes num_untainted num_tainted num_cordoned scale_down_order "
        "untainted_offsets untaint_order tainted_offsets reap_mask "
        "node_pods_remaining"
    ).split():
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )
