"""Pallas fused-aggregation kernel: exact parity against the XLA scatter path.

The Pallas sweep (ops/pallas_kernel.py) must be bit-identical to
``jax.ops.segment_sum`` — it feeds the same decision math the golden parity
suite certifies. Runs in interpret mode on the CPU test backend (conftest
pins JAX_PLATFORMS=cpu); on a real TPU the identical traced program compiles
through Mosaic.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from escalator_tpu.ops import pallas_kernel as pk  # noqa: E402
from escalator_tpu.ops import kernel  # noqa: E402


def _ref_sums(ids, valid, int_cols, cnt_cols, G):
    out = {}
    for name, col in {**int_cols, **cnt_cols}.items():
        out[name] = np.zeros(G, np.int64)
        np.add.at(out[name], ids, col.astype(np.int64))
    return out


def _sorted_ids(rng, P, G):
    """Group-contiguous ids, the packer's layout (some groups empty)."""
    counts = rng.multinomial(P, np.ones(G) / G)
    return np.repeat(np.arange(G, dtype=np.int32), counts)


@pytest.mark.parametrize("P,G", [(1, 1), (100, 4), (1333, 7), (5000, 300)])
def test_fused_sums_match_reference_sorted(P, G):
    rng = np.random.default_rng(P * 31 + G)
    ids = _sorted_ids(rng, P, G)
    valid = rng.random(P) < 0.9
    cpu = rng.integers(0, 2**40, P).astype(np.int64) * valid
    mem = rng.integers(0, 2**47, P).astype(np.int64) * valid
    cnt = valid.copy()

    got = pk.fused_segment_sums(
        jnp.asarray(ids),
        jnp.asarray(valid),
        {"cpu": jnp.asarray(cpu), "mem": jnp.asarray(mem)},
        {"cnt": jnp.asarray(cnt)},
        num_segments=G,
        interpret=True,
    )
    want = _ref_sums(ids, valid, {"cpu": cpu, "mem": mem}, {"cnt": cnt}, G)
    for name in want:
        np.testing.assert_array_equal(np.asarray(got[name]), want[name], err_msg=name)


def test_fused_sums_fallback_on_unsorted_layout():
    """Scattered ids break the window precondition -> XLA branch, same answer."""
    rng = np.random.default_rng(7)
    P, G = 4000, 1024
    ids = rng.integers(0, G, P).astype(np.int32)  # random => huge per-tile spread
    valid = np.ones(P, bool)
    cpu = rng.integers(0, 2**40, P).astype(np.int64)

    got = pk.fused_segment_sums(
        jnp.asarray(ids),
        jnp.asarray(valid),
        {"cpu": jnp.asarray(cpu)},
        {},
        num_segments=G,
        interpret=True,
    )
    want = _ref_sums(ids, valid, {"cpu": cpu}, {}, G)
    np.testing.assert_array_equal(np.asarray(got["cpu"]), want["cpu"])


def test_fused_sums_fallback_on_out_of_range_values():
    """Values >= 2^48 exceed the limb range -> XLA branch, still exact."""
    ids = np.zeros(600, np.int32)
    valid = np.ones(600, bool)
    big = np.full(600, 2**50, np.int64)  # >= 2^48 but the sum still fits int64
    got = pk.fused_segment_sums(
        jnp.asarray(ids), jnp.asarray(valid), {"v": jnp.asarray(big)}, {},
        num_segments=4, interpret=True,
    )
    assert int(got["v"][0]) == 600 * 2**50


def test_fused_sums_empty_groups_between_populated():
    """Empty groups inflate the window spread; either path must stay exact."""
    P = 1000
    ids = np.concatenate(
        [np.zeros(P // 2, np.int32), np.full(P - P // 2, 1900, np.int32)]
    )
    valid = np.ones(P, bool)
    cpu = np.full(P, 12345, np.int64)
    got = pk.fused_segment_sums(
        jnp.asarray(ids), jnp.asarray(valid), {"cpu": jnp.asarray(cpu)}, {},
        num_segments=2048, interpret=True,
    )
    want = _ref_sums(ids, valid, {"cpu": cpu}, {}, 2048)
    np.testing.assert_array_equal(np.asarray(got["cpu"]), want["cpu"])


def test_decide_pallas_impl_matches_xla_impl():
    """Full decision kernel: impl='pallas' is bit-identical to impl='xla'."""
    from escalator_tpu.core.arrays import ClusterArrays, GroupArrays, NodeArrays, PodArrays
    from escalator_tpu.core.arrays import NO_TAINT_TIME

    rng = np.random.default_rng(3)
    G, P, N = 64, 3000, 900
    pod_group = _sorted_ids(rng, P, G)
    node_group = _sorted_ids(rng, N, G)
    tainted = rng.random(N) < 0.3
    cluster = ClusterArrays(
        groups=GroupArrays(
            min_nodes=np.zeros(G, np.int32),
            max_nodes=np.full(G, 10**6, np.int32),
            taint_lower=np.full(G, 30, np.int32),
            taint_upper=np.full(G, 45, np.int32),
            scale_up_thr=np.full(G, 70, np.int32),
            slow_rate=np.ones(G, np.int32),
            fast_rate=np.full(G, 2, np.int32),
            locked=rng.random(G) < 0.1,
            requested_nodes=rng.integers(0, 5, G).astype(np.int32),
            cached_cpu_milli=np.full(G, 4000, np.int64),
            cached_mem_bytes=np.full(G, 16 * 10**9, np.int64),
            soft_grace_sec=np.full(G, 300, np.int64),
            hard_grace_sec=np.full(G, 900, np.int64),
            emptiest=np.zeros(G, bool),
            valid=np.ones(G, bool),
        ),
        pods=PodArrays(
            group=pod_group,
            cpu_milli=rng.integers(0, 16000, P).astype(np.int64),
            mem_bytes=rng.integers(0, 64 * 10**9, P).astype(np.int64),
            node=rng.integers(-1, N, P).astype(np.int32),
            valid=rng.random(P) < 0.95,
        ),
        nodes=NodeArrays(
            group=node_group,
            cpu_milli=np.full(N, 4000, np.int64),
            mem_bytes=np.full(N, 16 * 10**9, np.int64),
            creation_ns=rng.integers(1, 10**15, N).astype(np.int64),
            tainted=tainted,
            cordoned=(~tainted) & (rng.random(N) < 0.05),
            no_delete=rng.random(N) < 0.02,
            taint_time_sec=np.where(
                tainted, 1_700_000_000 - rng.integers(0, 2000, N), NO_TAINT_TIME
            ).astype(np.int64),
            valid=rng.random(N) < 0.97,
        ),
    )
    now = np.int64(1_700_000_000)
    a = kernel.decide_jit(cluster, now, impl="xla")
    b = kernel.decide_jit(cluster, now, impl="pallas")
    for f in (
        "status nodes_delta cpu_percent mem_percent cpu_request_milli "
        "mem_request_bytes cpu_capacity_milli mem_capacity_bytes num_pods "
        "num_nodes num_untainted num_tainted num_cordoned scale_down_order "
        "untainted_offsets untaint_order tainted_offsets reap_mask "
        "node_pods_remaining"
    ).split():
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )
