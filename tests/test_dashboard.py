"""The shipped Grafana dashboard must stay resolvable against the metrics this
process actually exports (reference ships the same pairing:
/root/reference/docs/grafana-dashboard.json over /root/reference/pkg/metrics/
metrics.go:12-230). A renamed collector or a typo'd panel query silently breaks
the dashboard in production — this locks the two files together in CI."""

from __future__ import annotations

import json
import pathlib
import re


REPO = pathlib.Path(__file__).resolve().parent.parent
DASHBOARD = REPO / "docs" / "grafana-dashboard.json"

#: Metrics the dashboard uses that are exported by OTHER cluster components
#: (kube-state-metrics), not by this process — same split as the reference's
#: Pod Phase panel, which queries kube-state-metrics too.
EXTERNAL_METRICS = {"kube_pod_status_phase"}


def _dashboard_exprs() -> list:
    data = json.loads(DASHBOARD.read_text())
    exprs = []

    def walk(obj):
        if isinstance(obj, dict):
            if isinstance(obj.get("expr"), str):
                exprs.append(obj["expr"])
            for v in obj.values():
                walk(v)
        elif isinstance(obj, list):
            for v in obj:
                walk(v)

    walk(data)
    return exprs


def _metric_tokens(expr: str) -> set:
    """Identifiers in a PromQL expression that look like our metric names.

    Restricting to the escalator prefixes keeps PromQL functions, label names
    and template variables out of the comparison.
    """
    toks = re.findall(r"[a-zA-Z_:][a-zA-Z0-9_:]*", expr)
    return {
        t for t in toks
        if t.startswith(("escalator_", "kube_"))
    }


def _exported_sample_names() -> set:
    from escalator_tpu.metrics import metrics

    names = set()
    for family in metrics.registry.collect():
        for sample in family.samples:
            names.add(sample.name)
        # histograms/counters may have no samples yet for some suffixes;
        # derive the canonical suffixed names from the family type too
        if family.type == "histogram":
            names.update(
                {family.name + s for s in ("_bucket", "_sum", "_count")}
            )
        elif family.type == "counter":
            names.add(family.name + "_total")
        else:
            names.add(family.name)
    return names


def test_every_dashboard_query_resolves():
    exprs = _dashboard_exprs()
    assert exprs, "dashboard has no queries — wrong file?"
    exported = _exported_sample_names()
    used = set().union(*(_metric_tokens(e) for e in exprs))
    unresolved = used - exported - EXTERNAL_METRICS
    assert not unresolved, (
        f"dashboard queries reference metrics this process does not export: "
        f"{sorted(unresolved)}"
    )


def test_dashboard_covers_reference_panel_set():
    """The panels the verdicts tracked as parity gaps stay present: scale lock,
    registration lag, Pod Phase, and the per-namespace running-pods panel."""
    text = DASHBOARD.read_text()
    data = json.loads(text)
    titles = [p.get("title", "") for p in data.get("panels", [])]
    for needle in ("Scale Lock", "Registration Lag", "Pod Phase"):
        assert any(needle.lower() in t.lower() for t in titles), (
            f"missing dashboard panel: {needle}; have {titles}"
        )
    assert "$namespace" in text, "per-namespace templated panel missing"
    # the reference templates on 4 variables (grafana-dashboard.json
    # templating list: nodegroup, namespace, cloud_provider_group,
    # cloud_provider); ours adds an explicit datasource on top
    var_names = {t["name"] for t in data["templating"]["list"]}
    assert {"datasource", "node_group", "namespace", "cloud_provider",
            "cloud_provider_group"} <= var_names, var_names
    # checked on the parsed exprs (the raw file escapes quotes), and with the
    # closing quote: bare "$cloud_provider" would match $cloud_provider_group
    assert any('=~"$cloud_provider"' in e for e in _dashboard_exprs()), (
        "cloud_provider variable is defined but filters no panel query"
    )


def test_histogram_queries_use_suffixed_series():
    """histogram_quantile() panels must query the *_bucket series — querying
    the bare family name returns nothing in Prometheus."""
    for expr in _dashboard_exprs():
        if "histogram_quantile" in expr:
            assert "_bucket" in expr, expr
