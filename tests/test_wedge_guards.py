"""The wedged-transport guard contract at raw library dispatch points.

The accelerator tunnel this repo targets can wedge so that the FIRST backend
dispatch hangs forever (no in-process timeout can interrupt it — see
jaxconfig.ensure_responsive_accelerator). CLI/backend/plugin entry points are
guarded at their construction sites, but raw library use — the verify doc's
surface 1, ``pack_cluster`` → ``decide_jit`` with nothing upstream — reaches
the backend first through the calls below. The round-5 drill caught
``decide_jit`` hanging 400+ s this way; these tests lock the fix: every raw
dispatch point must consult the (cached, fast-pathing) probe before its first
device touch, so a wedged transport degrades to CPU instead of hanging.

Under the test conftest the platform is cpu-pinned, so the probe fast-paths:
the spy observes the consult without paying a real probe.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from escalator_tpu import jaxconfig  # noqa: E402
from escalator_tpu.core import semantics as sem  # noqa: E402
from escalator_tpu.core.arrays import pack_cluster  # noqa: E402
from escalator_tpu.testsupport.builders import (  # noqa: E402
    NodeOpts, PodOpts, build_test_nodes, build_test_pods,
)

NOW = np.int64(0)


@pytest.fixture
def probe_calls(monkeypatch):
    """Count consults of the probe. The wrappers resolve the guard through
    jaxconfig at call time (late import or module-global lookup), so patching
    the jaxconfig attribute observes every dispatch-point path."""
    calls = []
    real = jaxconfig.ensure_responsive_accelerator

    def spy(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(jaxconfig, "ensure_responsive_accelerator", spy)
    return calls


def _tiny_cluster():
    cfg = sem.GroupConfig(
        min_nodes=1, max_nodes=30, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=70, slow_removal_rate=1,
        fast_removal_rate=2, soft_delete_grace_sec=300,
        hard_delete_grace_sec=900,
    )
    pods = build_test_pods(8, PodOpts(cpu=[500], mem=[10**9]))
    nodes = build_test_nodes(4, NodeOpts(cpu=4000, mem=16 * 10**9))
    return pack_cluster([(pods, nodes, cfg, sem.GroupState())])


def test_decide_jit_consults_probe(probe_calls):
    from escalator_tpu.ops import kernel

    out = kernel.decide_jit(_tiny_cluster(), NOW)
    assert probe_calls, "decide_jit dispatched without the wedge guard"
    # a real decision came back: 25% usage < taint_lower 30 → fast-rate -2,
    # matching the golden model for the same inputs
    assert int(out.nodes_delta[0]) == -2


def test_decide_jit_keeps_aggregates_parameter(probe_calls):
    # the guard wrapper must mirror decide()'s full signature AND forward it:
    # external raw users pass precomputed aggregates exactly like podaxis/grid
    # do with kernel.decide (a review of the wrapper caught this narrowing
    # once). Passing a deliberately doubled cpu sum makes forwarding
    # observable: 25% usage becomes 50%, flipping the decision from fast
    # scale-down (-2) to no-action (0) — a wrapper that drops the kwarg and
    # recomputes would return -2
    from escalator_tpu.ops import kernel

    c = _tiny_cluster()
    G = int(c.groups.valid.shape[0])
    N = int(c.nodes.valid.shape[0])
    cpu_req, mem_req, num_pods, per_node = kernel.aggregate_pods(
        c.pods, c.nodes.group, G, N, "xla")
    node_aggs = kernel.aggregate_nodes(c.nodes, G, "xla")
    doubled = (cpu_req * 2, mem_req, num_pods, per_node)
    out = kernel.decide_jit(c, NOW, impl="xla",
                            aggregates=(doubled, node_aggs))
    assert int(out.num_pods[0]) == 8
    assert int(out.nodes_delta[0]) == 0
    assert float(out.cpu_percent[0]) == 50.0


def test_sweep_deltas_jit_consults_probe(probe_calls):
    from escalator_tpu.ops import simulate

    simulate.sweep_deltas_jit(jax.device_put(_tiny_cluster()), 4)
    assert probe_calls


def test_sweep_deltas_by_type_jit_consults_probe(probe_calls):
    from escalator_tpu.ops import simulate

    simulate.sweep_deltas_by_type_jit(
        jax.device_put(_tiny_cluster()),
        np.array([1000, 4000], np.int64),
        np.array([16 * 10**9, 64 * 10**9], np.int64),
        4,
    )
    assert probe_calls


def test_mesh_constructors_consult_probe(probe_calls):
    from escalator_tpu.parallel import grid, mesh

    mesh.make_mesh()
    n_default = len(probe_calls)
    assert n_default, "make_mesh listed devices without the wedge guard"
    grid.make_grid_mesh()
    assert len(probe_calls) > n_default
    # an explicit device list means backends are the caller's problem —
    # no guard needed, and none should run
    devs = jax.devices()
    before = len(probe_calls)
    mesh.make_mesh(devices=devs)
    grid.make_grid_mesh(devices=devs, num_group_shards=len(devs))
    assert len(probe_calls) == before


def test_device_cluster_cache_consults_probe(probe_calls):
    from escalator_tpu.ops.device_state import DeviceClusterCache

    DeviceClusterCache(_tiny_cluster())
    assert probe_calls
    # explicit device skips the guard, same contract as the mesh constructors
    before = len(probe_calls)
    DeviceClusterCache(_tiny_cluster(), device=jax.devices()[0])
    assert len(probe_calls) == before
