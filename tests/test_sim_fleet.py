"""Fleet-scale soak: many nodegroups, full lifecycle, sharded mesh backend.

Drives the REAL controller through spike -> delivery -> drain -> scale-down
for 32 node groups at once, with the decision kernel sharded over the
8-device virtual mesh — the closed-loop, fleet-sized counterpart of the
single-group sim tests (reference analog: the multi-run convergence tests in
controller_scale_node_group_test.go, which cover one group on fakes).
"""

import numpy as np

from escalator_tpu import sim
from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.controller.backend import ShardedJaxBackend
from escalator_tpu.k8s.cache import EventfulClient
from escalator_tpu.testsupport.builders import NodeOpts, build_test_nodes

NUM_GROUPS = 32
KEY = "customer"


def _group_opts(i: int) -> ngmod.NodeGroupOptions:
    return ngmod.NodeGroupOptions(
        name=f"team{i}",
        label_key=KEY,
        label_value=f"team{i}",
        cloud_provider_group_name=f"team{i}-asg",
        min_nodes=1,
        max_nodes=60,
        taint_upper_capacity_threshold_percent=45,
        taint_lower_capacity_threshold_percent=30,
        scale_up_threshold_percent=70,
        slow_node_removal_rate=2,
        fast_node_removal_rate=4,
        soft_delete_grace_period="1m",
        hard_delete_grace_period="3m",
        scale_up_cool_down_period="4m",
    )


def test_fleet_spike_and_drain_converges():
    rng = np.random.default_rng(0)
    nodes = []
    for i in range(NUM_GROUPS):
        nodes += build_test_nodes(
            2, NodeOpts(cpu=2000, mem=8 * 10**9, label_key=KEY, label_value=f"team{i}"),
        )
    client = EventfulClient(nodes=nodes)
    groups = [_group_opts(i) for i in range(NUM_GROUPS)]

    workload = []
    for i in range(NUM_GROUPS):
        count = int(rng.integers(10, 40))
        workload.append({
            "at_tick": 0,
            "add_pods": {"count": count, "cpu_milli": 500,
                         "mem_bytes": 10**8,
                         "node_selector": {KEY: f"team{i}"}},
        })
        # drain: most pods finish mid-run
        workload.append({"at_tick": 14, "finish_pods": {"count": count - 2}})

    timeline = sim.run_simulation(
        groups, client, ticks=26, tick_interval_sec=60, node_ready_ticks=2,
        workload_events=workload, backend=ShardedJaxBackend(),
    )

    first, last = timeline[0], timeline[-1]
    # every group saw the spike and scaled up
    assert all(d > 0 for d in first["deltas"].values()), first["deltas"]
    peak_nodes = max(r["nodes"] for r in timeline)
    assert peak_nodes > NUM_GROUPS * 2  # the cloud delivered capacity
    # after the drain, every group is either converged or tainting down
    assert all(d <= 0 for d in last["deltas"].values()), last["deltas"]
    # scale-down engaged fleet-wide: tainted nodes present after the drain
    assert any(r["tainted"] > 0 for r in timeline[15:])
    # no group exceeded its max or dropped below min on the provider
    for ng in last["provider_targets"]:
        assert 1 <= last["provider_targets"][ng] <= 60


def test_fleet_provider_targets_track_demand():
    """Per-group targets must scale with each group's own demand (no
    cross-group bleed through the batched kernel)."""
    nodes = []
    for i in range(4):
        nodes += build_test_nodes(
            2, NodeOpts(cpu=2000, mem=8 * 10**9, label_key=KEY, label_value=f"team{i}"),
        )
    client = EventfulClient(nodes=nodes)
    groups = [_group_opts(i) for i in range(4)]
    # only team2 gets load
    workload = [{
        "at_tick": 0,
        "add_pods": {"count": 40, "cpu_milli": 500, "mem_bytes": 10**8,
                     "node_selector": {KEY: "team2"}},
    }]
    timeline = sim.run_simulation(
        groups, client, ticks=8, tick_interval_sec=60, node_ready_ticks=2,
        workload_events=workload, backend=ShardedJaxBackend(),
    )
    last = timeline[-1]["provider_targets"]
    assert last["team2"] > 2
    for other in ("team0", "team1", "team3"):
        assert last[other] <= 2, last
