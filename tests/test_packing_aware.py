"""packing_aware: the FFD-overflow delta replacing the whole-group average.

The reference documents that its delta math assumes one instance type and can
be wrong on heterogeneous nodes (/root/reference/docs/calculations.md:8,
docs/best-practices-issues-gotchas.md:36-38). These tests pin the two failure
modes the packing-aware option fixes — averaging over-asks when the pods
actually fit, and under-asks (zero) when a pod fits nowhere — plus cross-
backend parity of the override and config plumbing."""

import pytest

from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.controller.backend import (
    GoldenBackend,
    JaxBackend,
)
from escalator_tpu.core import semantics as sem
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_node,
    build_test_pod,
)

from tests.test_controller import BACKENDS, LABEL_KEY, LABEL_VALUE, World, make_opts


def _cfg(**kw):
    base = dict(
        min_nodes=0, max_nodes=100,
        taint_lower_percent=30, taint_upper_percent=45, scale_up_percent=70,
        slow_removal_rate=1, fast_removal_rate=2,
        soft_delete_grace_sec=300, hard_delete_grace_sec=900,
        packing_aware=True,
    )
    base.update(kw)
    return sem.GroupConfig(**base)


def _node(cpu, mem=16 * 10**9):
    return build_test_node(NodeOpts(
        cpu=cpu, mem=mem, label_key=LABEL_KEY, label_value=LABEL_VALUE))


def _pod(cpu, mem=10**9):
    return build_test_pod(PodOpts(
        cpu=[cpu], mem=[mem],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))


def test_average_over_asks_but_pods_fit():
    """Utilisation 75% > threshold 70% -> average delta 1; but one 750m pod per
    1000m node FITS, so the packed delta is 0 (no scale-up needed)."""
    nodes = [_node(1000), _node(1000)]
    pods = [_pod(750), _pod(750)]
    state = sem.GroupState()
    avg = sem.evaluate_node_group(pods, nodes, _cfg(packing_aware=False), state)
    packed = sem.evaluate_node_group(pods, nodes, _cfg(), sem.GroupState())
    assert avg.status == sem.DecisionStatus.OK and avg.nodes_delta == 1
    assert packed.status == sem.DecisionStatus.OK and packed.nodes_delta == 0


def test_average_misses_unplaceable_pod():
    """Utilisation 62.5% -> average says do nothing; but a 2500m pod fits NO
    2000m node (and never will) — packing claims one node for it instead of
    leaving it pending forever."""
    nodes = [_node(2000), _node(2000)]
    pods = [_pod(2500)]
    avg = sem.evaluate_node_group(
        pods, nodes, _cfg(packing_aware=False), sem.GroupState()
    )
    packed = sem.evaluate_node_group(pods, nodes, _cfg(), sem.GroupState())
    assert avg.status == sem.DecisionStatus.OK and avg.nodes_delta == 0
    assert packed.nodes_delta == 1


def test_heterogeneous_overflow_counts_template_nodes():
    """135% utilisation: the average asks for 2 nodes, but the six 450m pods
    pack two-per-1000m-node — one new template node suffices."""
    nodes = [_node(1000), _node(1000)]
    pods = [_pod(450) for _ in range(6)]
    avg = sem.evaluate_node_group(
        pods, nodes, _cfg(packing_aware=False), sem.GroupState()
    )
    packed = sem.evaluate_node_group(pods, nodes, _cfg(), sem.GroupState())
    assert avg.nodes_delta == 2
    assert packed.nodes_delta == 1


def test_scale_down_zone_is_untouched():
    """Packing replaces only non-negative deltas: the taint zones still use
    the reference's removal rates."""
    nodes = [_node(1000), _node(1000)]
    pods = [_pod(100)]  # 5% -> fast removal zone
    packed = sem.evaluate_node_group(pods, nodes, _cfg(), sem.GroupState())
    assert packed.nodes_delta == -2


def test_no_cached_capacity_requests_one_node():
    """Scale-from-zero with no template: mirror the reference's +1 convention."""
    packed = sem.evaluate_node_group(
        [_pod(500)], [], _cfg(min_nodes=0), sem.GroupState()
    )
    # zero capacity + zero untainted -> scale-from-zero sentinel path; packing
    # then sees no cached capacity and asks for one node to find out
    assert packed.nodes_delta == 1


def test_packing_budget_caps_the_delta():
    """Overflow beyond the budget: each unplaced pod still claims one node, so
    budget 2 with 5 one-per-node pods yields 2 + 3."""
    nodes = [_node(1000)]
    pods = [_pod(900) for _ in range(6)]
    packed = sem.evaluate_node_group(
        pods, nodes, _cfg(packing_budget=2), sem.GroupState()
    )
    assert packed.nodes_delta == 2 + 3


@pytest.fixture(params=list(BACKENDS), ids=list(BACKENDS))
def backend(request):
    return BACKENDS[request.param]()


def test_backend_parity_on_packing_groups(backend):
    """Every backend's packing-aware delta matches the golden model on a
    heterogeneous mix (distinct pod sizes keep FFD order-independent)."""
    opts = make_opts(packing_aware=True)
    nodes = [_node(4000), _node(2000), _node(1000)]
    # 4975m of requests on 7000m capacity = 71.07% > 70 -> scale-up zone
    pods = [_pod(c) for c in (1800, 1300, 900, 575, 400)]
    w = World(opts, nodes=nodes, pods=pods, backend=backend)
    w.tick()
    golden = sem.evaluate_node_group(
        w.state.pod_lister.list(), w.state.node_lister.list(),
        opts.to_group_config(), sem.GroupState(),
    )
    assert w.state.scale_delta == golden.nodes_delta


def test_controller_acts_on_packed_delta():
    """End-to-end: averaging would scale up, packing proves the pods fit, the
    provider is left alone."""
    opts = make_opts(packing_aware=True)
    nodes = [_node(1000), _node(1000)]
    pods = [_pod(750), _pod(750)]  # 75% utilisation, but one per node fits
    w = World(opts, nodes=nodes, pods=pods, backend=GoldenBackend())
    w.tick()
    assert w.state.scale_delta == 0
    assert w.group.increase_calls == []

    opts2 = make_opts(packing_aware=False)
    w2 = World(opts2, nodes=[_node(1000), _node(1000)],
               pods=[_pod(750), _pod(750)], backend=GoldenBackend())
    w2.tick()
    assert w2.state.scale_delta == 1
    assert w2.group.increase_calls == [1]


def test_budget_cap_through_device_kernel():
    """The device post-pass packs at the EXACT configured budget (padding the
    virtual-bin axis would let FFD spill past it and diverge from golden)."""
    opts = make_opts(packing_aware=True, packing_budget=2)
    nodes = [_node(1000)]
    pods = [_pod(900) for _ in range(6)]
    w = World(opts, nodes=nodes, pods=pods, backend=JaxBackend())
    w.tick()
    # 1 existing node holds one pod; budget 2 holds two; 3 unplaced claim one each
    assert w.state.scale_delta == 2 + 3


def test_yaml_config_and_validation():
    yaml_doc = """
node_groups:
  - name: pack
    label_key: customer
    label_value: pack
    cloud_provider_group_name: pack-asg
    min_nodes: 1
    max_nodes: 10
    taint_upper_capacity_threshold_percent: 45
    taint_lower_capacity_threshold_percent: 30
    scale_up_threshold_percent: 70
    slow_node_removal_rate: 1
    fast_node_removal_rate: 2
    soft_delete_grace_period: 5m
    hard_delete_grace_period: 15m
    scale_up_cool_down_period: 10m
    packing_aware: true
    packing_budget: 64
"""
    (opts,) = ngmod.unmarshal_node_group_options(yaml_doc)
    assert opts.packing_aware is True and opts.packing_budget == 64
    assert ngmod.validate_node_group(opts) == []
    cfg = opts.to_group_config()
    assert cfg.packing_aware is True and cfg.packing_budget == 64

    opts.packing_budget = 0
    assert any("packing_budget" in p for p in ngmod.validate_node_group(opts))
