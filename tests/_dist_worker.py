"""Worker process for the multi-process distributed test.

Joins a 2-process jax.distributed fleet on CPU, builds the global hybrid
(dcn, ici) mesh (one row per host), and runs a staged psum over it —
proving the multi-host communication backend end-to-end. argv: port, pid.
"""

import os
import sys
from functools import partial

os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from escalator_tpu.jaxconfig import shard_map  # noqa: E402
from escalator_tpu.parallel import distributed  # noqa: E402
from escalator_tpu.parallel.mesh import DCN_AXIS, ICI_AXIS  # noqa: E402


def main() -> None:
    port, pid = int(sys.argv[1]), int(sys.argv[2])
    ok = distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert ok, "distributed.initialize returned False with full config"
    assert jax.process_count() == 2
    assert len(jax.devices()) == 2  # one CPU device per process, global view

    mesh = distributed.global_hybrid_mesh()
    assert mesh.devices.shape == (2, 1), mesh.devices.shape
    # every dcn row must be one host
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1

    from jax.sharding import NamedSharding, PartitionSpec as P

    data = np.arange(4, dtype=np.int64)
    sharding = NamedSharding(mesh, P(DCN_AXIS))
    arr = jax.make_array_from_callback((4,), sharding, lambda idx: data[idx])

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P(DCN_AXIS), out_specs=P())
    def staged_total(x):
        s = jax.numpy.sum(x)
        s = jax.lax.psum(s, ICI_AXIS)  # fast intra-host axis first
        return jax.lax.psum(s, DCN_AXIS)  # then the cross-host hop

    total = int(staged_total(arr))
    assert total == 6, total
    print(f"WORKER_OK pid={pid} total={total}", flush=True)

    _grid_across_hosts(pid)


def _grid_across_hosts(pid: int) -> None:
    """The 2-D grid decider with its pod axis spanning the two processes:
    the pod-partial psum crosses hosts (the DCN hop), and the result must
    bit-match the process-local vmap(decide) on the same stacked cluster —
    the multi-host compute plane validated on the decision path itself,
    not just on a toy psum."""
    from jax.sharding import NamedSharding

    from escalator_tpu.ops import kernel
    from escalator_tpu.parallel import grid as gridlib
    from tests.test_grid import _stacked_cluster
    from tests.test_podaxis import ALL_FIELDS, NOW

    # same seed -> bit-identical host data on both processes; the shared
    # fixture also mixes invalid/cordoned/no_delete lanes the way the
    # single-host grid tests do
    stacked = _stacked_cluster(
        np.random.default_rng(42), Sg=1, G=2, P=17, N=6)  # 17: odd, pads
    now = NOW

    # expected: process-local vmap(decide) on this host's own device
    expected = jax.jit(jax.vmap(lambda c: kernel.decide(c, now)))(
        jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, jax.local_devices()[0]), stacked))

    gmesh = gridlib.make_grid_mesh(jax.devices(), num_group_shards=1)
    assert gmesh.shape == {"groups": 1, "pods": 2}, gmesh.shape
    padded = gridlib.pad_stacked_pods_for_grid(stacked, gmesh)
    specs = gridlib._cluster_specs()
    placed = jax.tree_util.tree_map(
        lambda leaf, spec: jax.make_array_from_callback(
            leaf.shape, NamedSharding(gmesh, spec), lambda idx, l=leaf: l[idx]),
        padded, specs)
    out = gridlib.make_grid_decider(gmesh)(placed, now)
    jax.block_until_ready(out.nodes_delta)

    for f in ALL_FIELDS:
        got = np.asarray(getattr(out, f))  # fully replicated -> local read
        np.testing.assert_array_equal(got, np.asarray(getattr(expected, f)), f)
    print(f"WORKER_GRID_OK pid={pid}", flush=True)


if __name__ == "__main__":
    main()
