"""REST apiserver adapter tests: wire protocol, informers, Lease election, and
full controller ticks over HTTP against the in-repo fake apiserver.

Reference analogs: client construction pkg/k8s/client.go:12-40, informer caches
pkg/k8s/cache.go:16-66, Lease election pkg/k8s/election.go:25-76, taint
GET-then-UPDATE pkg/k8s/taint.go:36-76."""

import time
from fractions import Fraction

import pytest
import yaml

from escalator_tpu.controller import controller as ctl
from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.controller.backend import GoldenBackend
from escalator_tpu.k8s import taint as tainting
from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.election import LeaderElectionConfig, LeaderElector
from escalator_tpu.k8s.restclient import (
    ApiError,
    ApiserverClient,
    ApiserverConfig,
    ConflictError,
    LeaseResourceLock,
    Transport,
    kubeconfig_config,
    node_from_json,
    node_to_json,
    parse_quantity,
    pod_from_json,
    pod_to_json,
    quantity_bytes,
    quantity_milli,
)
from escalator_tpu.testsupport.builders import NodeOpts, PodOpts, build_test_node, build_test_pod
from escalator_tpu.testsupport.cloud_provider import (
    MockBuilder,
    MockCloudProvider,
    MockNodeGroup,
)
from escalator_tpu.testsupport.fakeapiserver import FakeApiserver

TOKEN = "sekrit-token"


def _poll(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


@pytest.fixture()
def server():
    with FakeApiserver(token=TOKEN) as srv:
        yield srv


@pytest.fixture()
def client(server):
    c = ApiserverClient(
        ApiserverConfig(server.url, token=TOKEN), watch_timeout_sec=2)
    c.start(sync_timeout=10)
    yield c
    c.stop()


# ---------------------------------------------------------------------------
# quantity grammar (resource.Quantity semantics)
# ---------------------------------------------------------------------------


def test_parse_quantity_table():
    assert parse_quantity("500m") == Fraction(1, 2)
    assert quantity_milli("500m") == 500
    assert quantity_milli("2") == 2000
    assert quantity_milli("0.1") == 100
    assert quantity_milli("2.5") == 2500
    assert quantity_milli("1n") == 1  # MilliValue rounds UP
    assert quantity_bytes("1Gi") == 2**30
    assert quantity_bytes("128Mi") == 128 * 2**20
    assert quantity_bytes("1M") == 10**6
    assert quantity_bytes("1e3") == 1000
    assert quantity_bytes("1500") == 1500
    assert quantity_bytes("1.5Gi") == 3 * 2**29
    assert quantity_bytes("") == 0


def test_pod_json_mapping_roundtrip():
    pod = build_test_pod(PodOpts(
        name="web-1", cpu=[500, 250], mem=[10**9, 5 * 10**8],
        init_containers_cpu=[2000], init_containers_mem=[10**8],
        cpu_overhead=100, mem_overhead=10**7,
        node_selector_key="customer", node_selector_value="buildeng",
        node_affinity_key="tier", node_affinity_value="batch",
        owner="ReplicaSet", node_name="n1",
    ))
    back = pod_from_json(pod_to_json(pod))
    assert k8s.compute_pod_resource_request(back) == \
        k8s.compute_pod_resource_request(pod)
    assert back.node_selector == pod.node_selector
    assert back.node_name == "n1"
    assert back.owner_kind == "ReplicaSet"
    assert back.affinity.has_node_affinity
    term = back.affinity.node_affinity_required_terms[0]
    assert term.match_expressions[0].key == "tier"
    assert term.match_expressions[0].values == ("batch",)


def test_node_json_mapping_roundtrip():
    node = build_test_node(NodeOpts(
        name="n1", cpu=4000, mem=16 * 10**9, tainted=True,
        taint_time_sec=1_700_000_000, cordoned=True, no_delete=True,
        creation_time_ns=1_600_000_000 * 10**9,
    ))
    back = node_from_json(node_to_json(node))
    assert back.cpu_allocatable_milli == 4000
    assert back.mem_allocatable_bytes == 16 * 10**9
    assert back.unschedulable
    assert k8s.get_to_be_removed_time(back) == 1_700_000_000
    assert back.annotations[k8s.NODE_ESCALATOR_IGNORE_ANNOTATION]
    assert back.creation_time_ns == 1_600_000_000 * 10**9
    assert back.labels == node.labels


def test_node_json_parses_real_shapes():
    """Quantities as kubelet reports them: cpu in cores, memory in Ki."""
    node = node_from_json({
        "metadata": {"name": "ip-10-0-0-1",
                     "creationTimestamp": "2026-07-29T12:00:00Z",
                     "labels": {"customer": "shared"}},
        "spec": {"providerID": "aws:///us-east-1a/i-abc"},
        "status": {"allocatable": {"cpu": "3920m", "memory": "15246516Ki"}},
    })
    assert node.cpu_allocatable_milli == 3920
    assert node.mem_allocatable_bytes == 15246516 * 1024
    assert node.provider_id.endswith("i-abc")


# ---------------------------------------------------------------------------
# transport / auth
# ---------------------------------------------------------------------------


def test_bad_token_is_401(server):
    t = Transport(ApiserverConfig(server.url, token="wrong"))
    with pytest.raises(ApiError) as exc:
        t.request("GET", "/api/v1/nodes")
    assert exc.value.status == 401


# ---------------------------------------------------------------------------
# informers: list+watch, field selectors, relist
# ---------------------------------------------------------------------------


def test_informer_list_then_watch(server, client):
    assert client.list_nodes() == [] and client.list_pods() == []
    server.add_node(node_to_json(build_test_node(
        NodeOpts(name="n1", cpu=4000, mem=16 * 10**9))))
    server.add_pod(pod_to_json(build_test_pod(
        PodOpts(name="p1", cpu=[500], mem=[10**9]))))
    assert _poll(lambda: [n.name for n in client.list_nodes()] == ["n1"])
    assert _poll(lambda: [p.name for p in client.list_pods()] == ["p1"])
    # modification propagates
    server.add_node(node_to_json(build_test_node(
        NodeOpts(name="n1", cpu=8000, mem=16 * 10**9))))
    assert _poll(
        lambda: client.list_nodes()[0].cpu_allocatable_milli == 8000)
    # deletion propagates
    server.delete_object("/api/v1/nodes", "n1")
    assert _poll(lambda: client.list_nodes() == [])


def test_completed_pods_leave_the_cache(server, client):
    """status.phase!=Succeeded,!=Failed field selector: a pod completing is a
    DELETED event to the informer (pkg/k8s/cache.go:17)."""
    server.add_pod(pod_to_json(build_test_pod(
        PodOpts(name="job-1", namespace="default", cpu=[100], mem=[10**8]))))
    assert _poll(lambda: len(client.list_pods()) == 1)
    server.set_pod_phase("default", "job-1", "Succeeded")
    assert _poll(lambda: client.list_pods() == [])
    # and a Succeeded pod added later never shows up
    done = pod_to_json(build_test_pod(PodOpts(name="job-2", cpu=[1], mem=[1])))
    done["status"]["phase"] = "Failed"
    server.add_pod(done)
    server.add_pod(pod_to_json(build_test_pod(
        PodOpts(name="live", cpu=[1], mem=[1]))))
    assert _poll(lambda: [p.name for p in client.list_pods()] == ["live"])


def test_watch_expiry_triggers_relist(server, client):
    server.add_node(node_to_json(build_test_node(
        NodeOpts(name="n1", cpu=4000, mem=16 * 10**9))))
    assert _poll(lambda: len(client.list_nodes()) == 1)
    server.compact_history()  # next watch from the old rv gets 410
    time.sleep(2.2)  # let the in-flight short watch (2s) end and reconnect
    server.add_node(node_to_json(build_test_node(
        NodeOpts(name="n2", cpu=4000, mem=16 * 10**9))))
    assert _poll(lambda: len(client.list_nodes()) == 2, timeout=15)
    assert client._nodes.relists >= 1


def test_subscribe_replays_then_streams(server, client):
    server.add_node(node_to_json(build_test_node(
        NodeOpts(name="n1", cpu=4000, mem=16 * 10**9))))
    assert _poll(lambda: len(client.list_nodes()) == 1)
    seen = []
    client.subscribe(lambda e: seen.append((e.kind, e.type, getattr(e.obj, "name", ""))))
    assert ("node", "added", "n1") in seen  # replay
    server.add_pod(pod_to_json(build_test_pod(
        PodOpts(name="p1", cpu=[500], mem=[10**9]))))
    assert _poll(lambda: ("pod", "added", "p1") in seen)


# ---------------------------------------------------------------------------
# writes: GET-then-PUT, conflicts, events
# ---------------------------------------------------------------------------


def test_taint_flow_preserves_unknown_fields(server, client):
    raw = node_to_json(build_test_node(NodeOpts(name="n1", cpu=4000, mem=16 * 10**9)))
    raw["status"]["nodeInfo"] = {"kubeletVersion": "v1.29.0"}
    raw["status"]["conditions"] = [{"type": "Ready", "status": "True"}]
    server.add_node(raw)
    assert _poll(lambda: len(client.list_nodes()) == 1)

    node = client.get_node("n1")
    updated = tainting.add_to_be_removed_taint(node, client)
    assert k8s.get_to_be_removed_taint(updated) is not None

    stored = server.state.collections["/api/v1/nodes"]["n1"]
    assert stored["status"]["nodeInfo"]["kubeletVersion"] == "v1.29.0"
    assert stored["status"]["conditions"][0]["type"] == "Ready"
    assert any(t["key"] == k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY
               for t in stored["spec"]["taints"])

    # and removal round-trips too
    untainted = tainting.delete_to_be_removed_taint(updated, client)
    assert k8s.get_to_be_removed_taint(untainted) is None
    stored = server.state.collections["/api/v1/nodes"]["n1"]
    assert stored["spec"]["taints"] == []
    assert stored["status"]["nodeInfo"]["kubeletVersion"] == "v1.29.0"


def test_stale_resource_version_is_conflict(server, client):
    server.add_node(node_to_json(build_test_node(
        NodeOpts(name="n1", cpu=4000, mem=16 * 10**9))))
    assert _poll(lambda: len(client.list_nodes()) == 1)
    stale = dict(server.state.collections["/api/v1/nodes"]["n1"])
    stale["metadata"] = dict(stale["metadata"], resourceVersion="1")
    server.add_node(node_to_json(build_test_node(
        NodeOpts(name="n1", cpu=8000, mem=16 * 10**9))))  # bump rv
    with pytest.raises(ConflictError):
        client.transport.request("PUT", "/api/v1/nodes/n1", body=stale)


def test_delete_node_over_the_wire(server, client):
    server.add_node(node_to_json(build_test_node(
        NodeOpts(name="n1", cpu=4000, mem=16 * 10**9))))
    assert _poll(lambda: len(client.list_nodes()) == 1)
    client.delete_node("n1")
    assert server.state.collections["/api/v1/nodes"] == {}
    assert _poll(lambda: client.list_nodes() == [])


def test_events_posted(server, client):
    client.create_event(k8s.Event(
        reason="ScaleUpCloudProvider", message="increased by 3",
        involved_name="buildeng", timestamp_sec=1_700_000_000))
    evs = server.events
    assert len(evs) == 1
    assert evs[0]["reason"] == "ScaleUpCloudProvider"
    assert evs[0]["involvedObject"]["name"] == "buildeng"


# ---------------------------------------------------------------------------
# Lease election
# ---------------------------------------------------------------------------


def _elector(server, ident, **cfg):
    lock = LeaseResourceLock(
        Transport(ApiserverConfig(server.url, token=TOKEN)),
        namespace="kube-system", name="escalator-tpu")
    config = LeaderElectionConfig(
        lease_duration_sec=cfg.get("lease", 0.6),
        renew_deadline_sec=cfg.get("renew", 0.4),
        retry_period_sec=cfg.get("retry", 0.05),
    )
    return LeaderElector(lock, config, identity=ident)


def test_lease_election_single_winner_and_takeover(server):
    a = _elector(server, "holder-a")
    b = _elector(server, "holder-b")
    assert a.run(blocking_acquire_timeout=5)
    assert a.is_leader
    lease = server.lease("kube-system", "escalator-tpu")
    assert lease["spec"]["holderIdentity"] == "holder-a"

    # b cannot take a held, renewing lease
    assert not b.run(blocking_acquire_timeout=0.4)

    # a stops renewing; after expiry b takes over via CAS on the stale holder
    a.stop()
    assert b.run(blocking_acquire_timeout=10)
    lease = server.lease("kube-system", "escalator-tpu")
    assert lease["spec"]["holderIdentity"] == "holder-b"
    b.stop()


def test_lease_duration_is_positive_and_validated(server):
    """A real apiserver 422s leaseDurationSeconds <= 0 (ValidateLeaseSpec); the
    fake enforces the same, and the lock always writes a positive duration."""
    from escalator_tpu.k8s.election import LeaderRecord

    t = Transport(ApiserverConfig(server.url, token=TOKEN))
    lock = LeaseResourceLock(t, lease_duration_sec=15.0)
    now = time.time()
    assert lock.create_or_update(LeaderRecord("x", now, now), None)
    lease = server.lease("kube-system", "escalator-tpu")
    assert lease["spec"]["leaseDurationSeconds"] == 15
    # direct write of an invalid duration is rejected like a real apiserver
    bad = dict(lease)
    bad["spec"] = dict(lease["spec"], leaseDurationSeconds=0)
    with pytest.raises(ApiError) as exc:
        t.request("PUT",
                  "/apis/coordination.k8s.io/v1/namespaces/kube-system"
                  "/leases/escalator-tpu", body=bad)
    assert exc.value.status == 422


def test_lease_lock_survives_apiserver_outage(server):
    """Transient connection failure during acquisition = not-acquired, not a
    crash (an apiserver rolling restart must not kill HA standbys)."""
    t = Transport(ApiserverConfig("http://127.0.0.1:1", token=TOKEN))  # refused
    lock = LeaseResourceLock(t)
    from escalator_tpu.k8s.election import LeaderRecord

    now = time.time()
    assert lock.create_or_update(LeaderRecord("x", now, now), "x") is False
    elector = LeaderElector(lock, LeaderElectionConfig(
        lease_duration_sec=0.5, renew_deadline_sec=0.3, retry_period_sec=0.05))
    assert elector.run(blocking_acquire_timeout=0.3) is False  # no crash


def test_token_file_rotation(server, tmp_path):
    """Bound serviceaccount tokens rotate on disk; the transport must pick up
    the new token (client-go reloads; a cached startup token => 401 forever)."""
    tok = tmp_path / "token"
    tok.write_text("wrong")
    cfg = ApiserverConfig(server.url, token_file=str(tok))
    t = Transport(cfg)
    with pytest.raises(ApiError) as exc:
        t.request("GET", "/api/v1/nodes")
    assert exc.value.status == 401
    import os as _os

    tok.write_text(TOKEN)
    _os.utime(tok, (time.time() + 5, time.time() + 5))  # ensure mtime changes
    assert t.request("GET", "/api/v1/nodes")["kind"] == "NodeList"


def test_preexisting_empty_lease_is_claimable(server):
    """A Lease with no holderIdentity (released client-go-style or pre-created
    by a manifest) must be claimable via CAS PUT — a POST-only create path
    would 409-livelock forever."""
    from escalator_tpu.k8s.election import LeaderRecord

    t = Transport(ApiserverConfig(server.url, token=TOKEN))
    t.request("POST", "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases",
              body={"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                    "metadata": {"name": "escalator-tpu",
                                 "namespace": "kube-system"},
                    "spec": {}})
    lock = LeaseResourceLock(t)
    assert lock.get() is None  # holder-less reads as no record
    now = time.time()
    assert lock.create_or_update(LeaderRecord("claimer", now, now), None)
    lease = server.lease("kube-system", "escalator-tpu")
    assert lease["spec"]["holderIdentity"] == "claimer"


def test_micro_time_fraction_rollover():
    from escalator_tpu.k8s.restclient import _micro_time, _parse_micro_time

    t = 1_700_000_000.9999996  # naive per-field rounding emits ".1000000"
    assert abs(_parse_micro_time(_micro_time(t)) - (t + 0.0000004)) < 1e-5
    assert ".1000000" not in _micro_time(t)


def test_lease_cas_loses_race(server):
    """Two raw locks CAS-ing concurrently: exactly one create succeeds."""
    from escalator_tpu.k8s.election import LeaderRecord

    l1 = LeaseResourceLock(Transport(ApiserverConfig(server.url, token=TOKEN)))
    l2 = LeaseResourceLock(Transport(ApiserverConfig(server.url, token=TOKEN)))
    now = time.time()
    r1 = l1.create_or_update(LeaderRecord("x", now, now), None)
    r2 = l2.create_or_update(LeaderRecord("y", now, now), None)
    assert r1 and not r2
    # update with the wrong expected holder fails, right one succeeds
    assert not l2.create_or_update(LeaderRecord("y", now, now), "y")
    assert l1.create_or_update(LeaderRecord("x", now, now + 1), "x")


# ---------------------------------------------------------------------------
# kubeconfig
# ---------------------------------------------------------------------------


def test_kubeconfig_parsing(tmp_path, server):
    path = tmp_path / "kubeconfig"
    path.write_text(yaml.safe_dump({
        "current-context": "fake",
        "contexts": [{"name": "fake",
                      "context": {"cluster": "c", "user": "u",
                                  "namespace": "infra"}}],
        "clusters": [{"name": "c", "cluster": {"server": server.url}}],
        "users": [{"name": "u", "user": {"token": TOKEN}}],
    }))
    cfg = kubeconfig_config(str(path))
    assert cfg.base_url == server.url
    assert cfg.token == TOKEN
    assert cfg.namespace == "infra"


# ---------------------------------------------------------------------------
# end-to-end: controller ticks over HTTP
# ---------------------------------------------------------------------------

LABEL_KEY, LABEL_VALUE = "customer", "buildeng"


def _ng_opts(**kw):
    base = dict(
        name="buildeng", label_key=LABEL_KEY, label_value=LABEL_VALUE,
        cloud_provider_group_name="buildeng-asg",
        min_nodes=1, max_nodes=100,
        taint_upper_capacity_threshold_percent=45,
        taint_lower_capacity_threshold_percent=30,
        scale_up_threshold_percent=70,
        slow_node_removal_rate=1, fast_node_removal_rate=2,
        soft_delete_grace_period="5m", hard_delete_grace_period="15m",
        scale_up_cool_down_period="10m",
    )
    base.update(kw)
    return ngmod.NodeGroupOptions(**base)


def _seed_cluster(server, n_nodes, n_pods, pod_cpu=1500, pod_mem=6 * 10**9):
    for i in range(n_nodes):
        server.add_node(node_to_json(build_test_node(NodeOpts(
            name=f"n{i}", cpu=2000, mem=8 * 10**9,
            creation_time_ns=(i + 1) * 10**9))))
    for i in range(n_pods):
        server.add_pod(pod_to_json(build_test_pod(PodOpts(
            name=f"p{i}", cpu=[pod_cpu], mem=[pod_mem],
            node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))))


def _controller_over(client, opts, target_size):
    provider = MockCloudProvider()
    group = MockNodeGroup("buildeng-asg", "buildeng", min_size=opts.min_nodes,
                          max_size=opts.max_nodes, target_size=target_size)
    provider.register_node_group(group)
    controller = ctl.Controller(ctl.Opts(
        client=client, node_groups=[opts],
        cloud_provider_builder=MockBuilder(provider),
        scan_interval_sec=60, backend=GoldenBackend(),
    ))
    return controller, group


def test_controller_scales_up_over_http(server, client):
    _seed_cluster(server, n_nodes=2, n_pods=8)  # way over capacity
    assert _poll(lambda: len(client.list_nodes()) == 2
                 and len(client.list_pods()) == 8)
    controller, group = _controller_over(client, _ng_opts(), target_size=2)
    controller.run_once()
    assert group.target_size() > 2


def test_controller_taints_over_http(server, client):
    # 6 idle nodes, one tiny pod: utilisation far below the taint threshold
    _seed_cluster(server, n_nodes=6, n_pods=1, pod_cpu=50, pod_mem=10**8)
    assert _poll(lambda: len(client.list_nodes()) == 6
                 and len(client.list_pods()) == 1)
    controller, _ = _controller_over(client, _ng_opts(), target_size=6)
    controller.run_once()
    stored = server.state.collections["/api/v1/nodes"]
    tainted = [
        name for name, obj in stored.items()
        if any(t["key"] == k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY
               for t in (obj.get("spec", {}).get("taints") or []))
    ]
    assert len(tainted) >= 1
    # oldest-first: n0 has the earliest creationTimestamp
    assert "n0" in tainted


def test_native_backend_over_http(server, client):
    """The full event path: apiserver watch -> informer -> WatchBridge ->
    native store -> kernel decision."""
    from escalator_tpu.controller.native_backend import make_native_backend

    _seed_cluster(server, n_nodes=2, n_pods=8)
    assert _poll(lambda: len(client.list_nodes()) == 2
                 and len(client.list_pods()) == 8)
    opts = _ng_opts()
    backend = make_native_backend(client, [opts])
    provider = MockCloudProvider()
    group = MockNodeGroup("buildeng-asg", "buildeng", min_size=1,
                          max_size=100, target_size=2)
    provider.register_node_group(group)
    controller = ctl.Controller(ctl.Opts(
        client=client, node_groups=[opts],
        cloud_provider_builder=MockBuilder(provider),
        scan_interval_sec=60, backend=backend,
    ))
    controller.run_once()
    assert group.target_size() > 2


def test_cli_once_against_fake_apiserver(server, tmp_path, capsys):
    """cli.main --kubeconfig --once --leader-elect drives config discovery,
    informer sync, Lease election and a full tick over the wire."""
    import json as jsonmod

    from escalator_tpu import cli

    _seed_cluster(server, n_nodes=2, n_pods=8)
    kubeconfig = tmp_path / "kubeconfig"
    kubeconfig.write_text(yaml.safe_dump({
        "current-context": "fake",
        "contexts": [{"name": "fake", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {"server": server.url}}],
        "users": [{"name": "u", "user": {"token": TOKEN}}],
    }))
    ngfile = tmp_path / "nodegroups.yaml"
    ngfile.write_text(yaml.safe_dump({"node_groups": [{
        "name": "buildeng",
        "label_key": LABEL_KEY, "label_value": LABEL_VALUE,
        "cloud_provider_group_name": "buildeng-asg",
        "min_nodes": 1, "max_nodes": 100,
        "taint_upper_capacity_threshold_percent": 45,
        "taint_lower_capacity_threshold_percent": 30,
        "scale_up_threshold_percent": 70,
        "slow_node_removal_rate": 1, "fast_node_removal_rate": 2,
        "soft_delete_grace_period": "5m", "hard_delete_grace_period": "15m",
        "scale_up_cool_down_period": "10m",
    }]}))
    rc = cli.main([
        "--nodegroups", str(ngfile),
        "--kubeconfig", str(kubeconfig),
        "--backend", "golden",
        "--leader-elect",
        "--leader-elect-lease-namespace", "default",
        "--once",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    doc = jsonmod.loads(out)
    assert doc["deltas"]["buildeng"] > 0
    # the election left a Lease behind and recorded the event
    lease = server.lease("default", "escalator-tpu")
    assert lease is not None and lease["spec"]["holderIdentity"]
    assert any(e["reason"] == "LeaderElected" for e in server.events)
