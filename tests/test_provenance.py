"""Decision provenance observatory (round-19 tentpole).

Four layers under test:

- the explain CONTRACT (observability/provenance.py): the term glossary /
  column / branch tables stay in sync with the kernel's explain entries,
  the cross-check compares on raw float bits (NaN and -0.0 drifts must
  not hide behind ``==``), and explanation documents name exactly the one
  controller.go:332-351 threshold arm the fired gates imply;
- the decision HISTORY + flap watchdog: bounded per-key rings, the
  sign-alternation / status-churn detectors (holds don't break an
  oscillation; steady workloads never reach the scan), per-window
  re-fire debounce, rate-limited ``reason="flap"`` dumps with
  explanations, and the env-knob parse discipline;
- the traced explain PATH: ``IncrementalDecider.explain`` bit-cross-
  checks the re-derived calculus against the committed columns across a
  randomized 30-tick soak (pod churn + taint/cordon/drain flips), and
  ``debug-explain --replay`` re-executes the recorded ring from a
  snapshot to byte-identical explanations — plus the inertness law: a
  provenance-armed process traces byte-identical jaxprs;
- the fleet end: ``explain_tenant`` parity against the served columns,
  the wildcard explainer registration, and the digest fast path staging
  cached answers into the same history the dispatch path feeds.
"""

from __future__ import annotations

import copy
import gc
import json
import logging
import time

import numpy as np
import pytest

from escalator_tpu.observability import provenance

NOW = 1_700_000_000


@pytest.fixture(autouse=True)
def _provenance_hygiene():
    """History/flap/mismatch state is process-global; every test starts
    and ends clean (the dump worker drains before the reset so a late
    flap dump never lands in the next test's tmpdir)."""
    provenance.reset()
    yield
    provenance.FLAPS.drain()
    provenance.reset()


def _kernel_terms(seed: int = 0) -> dict:
    """One real explain-kernel evaluation as a host term dict — the
    fixture every contract test builds documents from."""
    from escalator_tpu.analysis import registry
    from escalator_tpu.ops import kernel

    terms = kernel._explain_decide_raw(*registry._explain_decide_args(seed))
    return {k: np.asarray(v) for k, v in terms.items()}


def _committed_from(terms: dict) -> dict:
    return {f: np.array(terms[f]) for f in provenance.COLUMN_FIELDS}


# ---------------------------------------------------------------------------
# contract sync: provenance's tables are twins of the kernel's
# ---------------------------------------------------------------------------


class TestContractSync:
    def test_column_fields_and_branch_tables_match_kernel(self):
        from escalator_tpu.ops import kernel

        assert provenance.COLUMN_FIELDS == tuple(
            kernel.GROUP_DECISION_FIELDS)
        assert provenance.THRESHOLD_BRANCHES == tuple(
            kernel.EXPLAIN_THRESHOLD_BRANCHES)
        assert provenance.STATUS_BRANCHES == tuple(
            kernel.EXPLAIN_STATUS_BRANCHES)

    def test_glossary_names_every_explain_term(self):
        terms = _kernel_terms()
        missing = set(terms) - set(provenance.TERM_GLOSSARY)
        assert not missing, f"explain terms without a glossary row: {missing}"
        assert set(provenance.COLUMN_FIELDS) <= set(terms)

    def test_registry_dtype_contract_matches_live_terms(self):
        from escalator_tpu.analysis.registry import EXPLAIN_DTYPES

        terms = _kernel_terms()
        for name, dtype in EXPLAIN_DTYPES.items():
            assert str(terms[name].dtype) == dtype, name


# ---------------------------------------------------------------------------
# cross_check: raw-bit float semantics
# ---------------------------------------------------------------------------


class TestCrossCheck:
    def test_identical_columns_are_clean(self):
        terms = _kernel_terms()
        assert provenance.cross_check(terms, _committed_from(terms)) == []

    def test_integer_drift_is_a_named_finding(self):
        terms = _kernel_terms()
        committed = _committed_from(terms)
        committed["nodes_delta"][2] += 1
        findings = provenance.cross_check(terms, committed)
        assert len(findings) == 1
        f = findings[0]
        assert (f["group"], f["field"]) == (2, "nodes_delta")
        assert f["explained"] == f["committed"] - 1

    def test_float_columns_compare_on_raw_bits(self):
        terms = dict(_kernel_terms())
        committed = _committed_from(terms)
        cpu = np.array(terms["cpu_percent"])
        # same-bits NaN is NOT a drift; 0.0 vs -0.0 IS (== would pass both)
        cpu[0] = np.float64("nan")
        committed["cpu_percent"][0] = np.float64("nan")
        cpu[1] = 0.0
        committed["cpu_percent"][1] = -0.0
        terms["cpu_percent"] = cpu
        findings = provenance.cross_check(terms, committed)
        assert [(f["group"], f["field"]) for f in findings] == [
            (1, "cpu_percent")]

    def test_dirty_groups_are_skipped(self):
        terms = _kernel_terms()
        committed = _committed_from(terms)
        committed["status"][3] += 1
        G = committed["status"].shape[0]
        dirty = np.zeros(G, bool)
        dirty[3] = True
        assert provenance.cross_check(terms, committed, skip=dirty) == []
        assert provenance.cross_check(terms, committed) != []

    def test_shape_mismatch_is_one_finding_not_a_crash(self):
        terms = _kernel_terms()
        committed = _committed_from(terms)
        committed["status"] = committed["status"][:-1]
        findings = [f for f in provenance.cross_check(terms, committed)
                    if f["field"] == "status"]
        assert findings == [{
            "group": -1, "field": "status",
            "explained": [terms["status"].shape[0]],
            "committed": [terms["status"].shape[0] - 1]}]


# ---------------------------------------------------------------------------
# explanation documents
# ---------------------------------------------------------------------------


class TestBuildExplanations:
    def test_documents_name_exactly_the_fired_threshold_arm(self):
        terms = _kernel_terms()
        docs = provenance.build_explanations(
            terms, committed=_committed_from(terms))
        assert len(docs) == terms["status"].shape[0]
        for d in docs:
            assert "mismatches" not in d
            assert d["threshold_branch"] in provenance.THRESHOLD_BRANCHES
            assert d["status_branch"] in provenance.STATUS_BRANCHES
            # the ONE arm the fired gates imply, in the kernel's priority
            fired = [k for k in ("gate_down_fast", "gate_down_slow",
                                 "gate_scale_up") if d["gates"][k]]
            arm = {"gate_down_fast": "scale_down_fast",
                   "gate_down_slow": "scale_down_slow",
                   "gate_scale_up": "scale_up"}
            assert d["threshold_branch"] == (
                arm[fired[0]] if fired else "hold")
            assert set(d["config"]) == set(provenance._CONFIG_KEYS)
            assert not any(k.startswith(("gate_", "cfg_"))
                           for k in d["terms"])

    def test_groups_filter_and_candidate_attachment(self):
        terms = _kernel_terms()
        docs = provenance.build_explanations(
            terms, groups=[3, 1, 99], candidates={3: [5, 6], 1: []})
        assert [d["group"] for d in docs] == [3, 1]
        assert docs[0]["scale_down_candidates"] == [5, 6]
        assert docs[1]["scale_down_candidates"] == []
        docs = provenance.build_explanations(terms, groups=[1])
        assert "scale_down_candidates" not in docs[0]   # none attached

    def test_dirty_marks_stale_and_suppresses_the_finding(self):
        terms = _kernel_terms()
        committed = _committed_from(terms)
        committed["nodes_delta"][2] += 5
        G = committed["status"].shape[0]
        dirty = np.zeros(G, bool)
        dirty[2] = True
        docs = provenance.build_explanations(terms, committed, dirty=dirty)
        assert docs[2]["stale"] is True
        assert "mismatches" not in docs[2]
        docs = provenance.build_explanations(terms, committed)
        assert docs[2]["mismatches"][0]["field"] == "nodes_delta"


def test_candidate_windows_slices_and_truncates():
    order = np.arange(10)
    offsets = np.array([0, 3, 3, 9])
    wins = provenance.candidate_windows(order, offsets, max_per_group=4)
    assert wins == {0: [0, 1, 2], 2: [3, 4, 5, 6]}   # empty g=1 absent


# ---------------------------------------------------------------------------
# decision-diff forensics
# ---------------------------------------------------------------------------


def _doc(group=0, status=0, delta=0, tb="hold", sb=None, terms=None,
         config=None, gates=None):
    return {"group": group, "status": status, "status_name": f"S{status}",
            "nodes_delta": delta, "threshold_branch": tb,
            "status_branch": sb or provenance.STATUS_BRANCHES[-1],
            "stale": False, "terms": dict(terms or {}),
            "config": dict(config or {}), "gates": dict(gates or {})}


_CFG = {"cfg_scale_up_threshold": 70, "cfg_taint_lower": 40,
        "cfg_taint_upper": 55, "cfg_min_nodes": 1, "cfg_max_nodes": 10}


class TestDiffForensics:
    def test_attribution_names_the_crossed_threshold(self):
        a = _doc(terms={"max_percent": 60.0, "num_nodes": 3,
                        "num_untainted": 3}, config=_CFG,
                 gates={"gate_scale_up": False})
        b = _doc(status=4, delta=2, tb="scale_up",
                 terms={"max_percent": 80.0, "num_nodes": 3,
                        "num_untainted": 3}, config=_CFG,
                 gates={"gate_scale_up": True})
        res = provenance.diff_explanations([a], [b])
        assert res["unchanged_groups"] == 0
        (ch,) = res["changed"]
        assert ch["nodes_delta"] == [0, 2]
        assert ch["term_deltas"]["max_percent"] == [60.0, 80.0]
        notes = ch["attribution"]
        assert ("max_percent crossed scale_up_threshold "
                "(60.0 -> 80.0, threshold 70)") in notes
        assert "threshold branch hold -> scale_up" in notes
        assert "gate_scale_up False -> True" in notes

    def test_config_change_is_noted_once(self):
        # two crossing rules watch cfg_min_nodes (num_nodes AND
        # num_untainted) — a changed knob must not print twice
        terms = {"max_percent": 50.0, "num_nodes": 3, "num_untainted": 3}
        a = _doc(terms=terms, config=_CFG)
        b = _doc(status=2, terms=terms,
                 config=dict(_CFG, cfg_min_nodes=5))
        (ch,) = provenance.diff_explanations([a], [b])["changed"]
        assert ch["attribution"].count("cfg_min_nodes changed 1 -> 5") == 1

    def test_membership_and_unchanged_accounting(self):
        shared = _doc(group=1, status=0, delta=0)
        res = provenance.diff_explanations(
            [_doc(group=0), shared], [copy.deepcopy(shared), _doc(group=2)])
        assert res["changed"] == []
        assert res["unchanged_groups"] == 1
        assert res["only_in_a"] == [0] and res["only_in_b"] == [2]


# ---------------------------------------------------------------------------
# decision history ring
# ---------------------------------------------------------------------------


class TestDecisionHistory:
    def test_push_window_and_group_view(self):
        h = provenance.DecisionHistory(depth=3)
        for t in range(5):
            tick, window = h.push(
                "k", np.array([0, 4]), np.array([t, -t]))
        assert tick == 5 and len(window) == 3
        full = h.history("k")
        assert [r["tick"] for r in full] == [3, 4, 5]
        assert full[-1]["nodes_delta"] == [4, -4]
        g1 = h.history("k", group=1)
        assert [r["status"] for r in g1] == [4, 4, 4]
        assert h.history("k", group=7) == []   # out of range: empty view

    def test_explicit_tick_then_sequence_resumes(self):
        h = provenance.DecisionHistory(depth=4)
        h.push("k", np.zeros(1), np.zeros(1), tick=41)
        tick, _ = h.push("k", np.zeros(1), np.zeros(1))
        assert tick == 42

    def test_shape_change_restarts_the_ring(self):
        h = provenance.DecisionHistory(depth=8)
        h.push("k", np.zeros(4), np.zeros(4))
        h.push("k", np.zeros(4), np.zeros(4))
        _, window = h.push("k", np.zeros(6), np.zeros(6))
        assert len(window) == 1   # mixed widths would stack meaninglessly

    def test_key_lru_bound(self):
        h = provenance.DecisionHistory(depth=2, max_keys=2)
        h.push("a", np.zeros(1), np.zeros(1))
        h.push("b", np.zeros(1), np.zeros(1))
        h.push("a", np.zeros(1), np.zeros(1))   # refresh a
        h.push("c", np.zeros(1), np.zeros(1))   # evicts b (LRU)
        assert set(h.keys()) == {"a", "c"}


# ---------------------------------------------------------------------------
# flap watchdog
# ---------------------------------------------------------------------------


def _feed(key, deltas, statuses=None, G=2, start_tick=1):
    """Drive the singleton via the real staging path (no active timeline:
    records feed through immediately). Group 0 carries the pattern."""
    for i, d in enumerate(deltas):
        delta = np.zeros(G, np.int64)
        delta[0] = d
        status = np.zeros(G, np.int64)
        if statuses is not None:
            status[0] = statuses[i]
        provenance.stage(key, status, delta, tick=start_tick + i)


def _flap_events():
    from escalator_tpu.observability import journal

    return [e for e in journal.JOURNAL.snapshot()
            if e.get("kind") == "group-flap"]


def _counter(name, labels=None):
    from escalator_tpu.metrics import metrics

    return metrics.registry.get_sample_value(name, labels or {}) or 0.0


class TestFlapWatchdog:
    def test_steady_and_monotone_workloads_are_silent(self, monkeypatch):
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_WINDOW", "6")
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_MIN_ALTERNATIONS", "3")
        base_events = len(_flap_events())
        _feed("idle", [0] * 10)          # prefiltered: never reaches a scan
        _feed("monotone", [1] * 10)      # moves, but never alternates
        assert provenance.FLAPS.flaps == 0
        assert len(_flap_events()) == base_events

    def test_oscillation_fires_counts_journals_and_dumps(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_WINDOW", "6")
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_MIN_ALTERNATIONS", "3")
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_DUMP_INTERVAL_SEC", "3600")
        provenance.register_explainer(
            "osc", lambda key, groups: [{"group": int(g), "key": key}
                                        for g in (groups or [0])])
        try:
            before = _counter("escalator_tpu_fleet_group_flaps_total",
                              {"klass": "delta_sign"})
            _feed("osc", [1, -1] * 4)
            assert provenance.FLAPS.flaps >= 1
            provenance.FLAPS.drain()
            assert _counter("escalator_tpu_fleet_group_flaps_total",
                            {"klass": "delta_sign"}) >= before + 1
            ev = [e for e in _flap_events() if e.get("key") == "osc"]
            assert ev and ev[0]["groups"] == [0] and ev[0]["dumped"] is True
            assert provenance.FLAPS.top_flapping()[0]["key"] == "osc"
            assert list(provenance.FLAPS.recent)[-1]["klass"] == "delta_sign"
            dumps = sorted(tmp_path.glob(
                "escalator-tpu-flight-flap-*.json"))
            assert dumps, "no flap dump landed"
            flap = json.loads(dumps[-1].read_text())["flap"]
            assert flap["key"] == "osc" and flap["groups"] == [0]
            assert flap["findings"][0]["klass"] == "delta_sign"
            assert flap["findings"][0]["history"]   # the offending window
            assert flap["explanations"] == [{"group": 0, "key": "osc"}]
        finally:
            provenance.unregister_explainer("osc")

    def test_holds_do_not_break_an_oscillation(self, monkeypatch):
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_WINDOW", "8")
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_MIN_ALTERNATIONS", "3")
        _feed("thrash", [1, 0, -1, 0, 1, 0, -1])   # the classic thrash
        assert provenance.FLAPS.flaps >= 1
        assert list(provenance.FLAPS.recent)[-1]["klass"] == "delta_sign"

    def test_refire_debounce_and_dump_rate_limit(self, monkeypatch):
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_WINDOW", "4")
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_MIN_ALTERNATIONS", "2")
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_DUMP_INTERVAL_SEC", "3600")
        base_events = len(_flap_events())
        _feed("sustained", [1, -1] * 6)   # ticks 1..12
        provenance.FLAPS.drain()
        # one incident per full window (ticks 3, 7, 11), one dump per
        # interval — the journal keeps the rate-limited re-fires
        assert provenance.FLAPS.flaps == 3
        assert provenance.FLAPS.dumps == 1
        dumped = [e["dumped"] for e in _flap_events()[base_events:]]
        assert dumped == [True, False, False]

    def test_status_churn_between_two_codes(self, monkeypatch):
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_WINDOW", "8")
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_MIN_ALTERNATIONS", "2")
        _feed("bounce", [0] * 8, statuses=[0, 4] * 4)
        assert provenance.FLAPS.flaps >= 1
        assert list(provenance.FLAPS.recent)[-1]["klass"] == "status_churn"

    def test_window_off_disables_detection(self, monkeypatch):
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_WINDOW", "off")
        _feed("osc-off", [1, -1] * 6)
        assert provenance.FLAPS.flaps == 0

    def test_bad_env_warns_once_and_defaults(self, monkeypatch, caplog):
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_WINDOW", "banana")
        with caplog.at_level(logging.WARNING,
                             logger="escalator_tpu.observability"):
            _feed("osc-bad", [1, -1] * 4)   # default window 8 / min_alt 3
        assert provenance.FLAPS.flaps >= 1
        assert "using default" in caplog.text


# ---------------------------------------------------------------------------
# the staging feed (timeline stash -> root-complete drain)
# ---------------------------------------------------------------------------


class TestStagingFeed:
    def test_stage_rides_the_timeline_until_root_completes(self):
        from escalator_tpu.observability import spans

        with spans.span("prov_root"):
            provenance.stage("tl-key", np.zeros(2, np.int64),
                             np.zeros(2, np.int64))
            assert "tl-key" not in provenance.HISTORY.keys()
        # the flight recorder's root-complete hook drained the stash
        assert "tl-key" in provenance.HISTORY.keys()

    def test_stage_without_timeline_feeds_immediately(self):
        provenance.stage("raw-key", np.zeros(2, np.int64),
                         np.zeros(2, np.int64), tick=9)
        hist = provenance.HISTORY.history("raw-key")
        assert [r["tick"] for r in hist] == [9]


# ---------------------------------------------------------------------------
# mismatch reporting
# ---------------------------------------------------------------------------


class TestMismatchReporting:
    def test_counter_journal_and_rate_limited_dump(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_DUMP_INTERVAL_SEC", "3600")
        before = _counter("escalator_tpu_provenance_explain_mismatches_total")
        mm = [{"group": 0, "field": "status", "explained": 1,
               "committed": 0}]
        provenance.report_mismatches("unit", mm,
                                     explanations=[{"group": 0}])
        provenance.report_mismatches("unit", mm)   # inside the interval
        assert provenance.mismatch_total() == 2
        assert _counter(
            "escalator_tpu_provenance_explain_mismatches_total"
        ) == before + 2
        from escalator_tpu.observability import journal

        ev = [e for e in journal.JOURNAL.snapshot()
              if e.get("kind") == "explain-mismatch"
              and e.get("context") == "unit"]
        assert len(ev) == 2 and ev[0]["fields"] == ["status"]
        dumps = sorted(tmp_path.glob(
            "escalator-tpu-flight-explain-mismatch-*.json"))
        assert len(dumps) == 1   # the second burst was rate-limited
        extra = json.loads(dumps[0].read_text())["explain_mismatch"]
        assert extra["context"] == "unit" and extra["mismatches"] == mm
        assert extra["explanations"] == [{"group": 0}]

    def test_empty_report_is_a_noop(self):
        provenance.report_mismatches("unit", [])
        assert provenance.mismatch_total() == 0


# ---------------------------------------------------------------------------
# explainer registry + dump/health surfacing
# ---------------------------------------------------------------------------


class TestExplainerRegistry:
    def test_exact_key_wins_over_wildcard_and_dicts_unwrap(self):
        provenance.register_explainer(
            "*", lambda key, groups: [{"group": 0, "via": "wildcard"}])
        provenance.register_explainer(
            "t1", lambda key, groups: {"explanations":
                                       [{"group": 0, "via": "exact"}]})
        try:
            assert provenance.explain_for("t1")[0]["via"] == "exact"
            assert provenance.explain_for("anything")[0]["via"] == "wildcard"
        finally:
            provenance.unregister_explainer("*")
            provenance.unregister_explainer("t1")
        assert provenance.explain_for("t1") is None

    def test_bound_methods_are_held_weakly(self):
        class Engine:
            def explain(self, key, groups):
                return [{"group": 0}]

        eng = Engine()
        provenance.register_explainer("weak", eng.explain)
        assert provenance.explain_for("weak") == [{"group": 0}]
        del eng
        gc.collect()
        assert provenance.explain_for("weak") is None   # self-unregistered


class TestSurfacing:
    def test_dump_section_is_none_when_clean(self):
        assert provenance.dump_section() is None
        assert provenance.dump_section({"tail": {"root": "tick"}}) is None

    def test_dump_section_carries_history_and_explanations(self):
        provenance.stage("t9", np.zeros(2, np.int64),
                         np.zeros(2, np.int64), tick=1)
        provenance.register_explainer(
            "t9", lambda key, groups: [{"group": 0}])
        try:
            sec = provenance.dump_section({"tail": {"root": "fleet/t9"}})
            assert sec["history"]["t9"][0]["tick"] == 1
            assert sec["explanations"]["t9"] == [{"group": 0}]
            # a flap incident's own key skips the duplicate explain gather
            sec = provenance.dump_section({"flap": {"key": "t9"}})
            assert "explanations" not in sec
        finally:
            provenance.unregister_explainer("t9")

    def test_health_section_fields(self):
        provenance.stage("hk", np.zeros(1, np.int64),
                         np.zeros(1, np.int64), tick=1)
        h = provenance.health_section()
        assert h["history_keys"] == 1
        assert h["history_depth"] == provenance.HISTORY.depth
        for k in ("flaps_total", "flap_dumps",
                  "explain_mismatches_total", "top_flapping"):
            assert k in h


# ---------------------------------------------------------------------------
# inertness: provenance armed changes no traced program
# ---------------------------------------------------------------------------


def test_jaxprs_byte_identical_with_provenance_armed(monkeypatch):
    """The observatory lives strictly host-side: tracing the pre-existing
    decide entries with provenance fully armed (history staged, flap knobs
    set, a live explainer registered) yields jaxprs byte-identical to a
    disarmed process — the same inertness law the span layer obeys."""
    import jax

    from escalator_tpu.analysis.registry import default_registry

    entries = {e.name: e for e in default_registry()}
    for name in ("kernel.decide", "kernel.delta_decide"):
        traced = entries[name].build()

        def jaxpr_text():
            return str(jax.make_jaxpr(traced.fn)(*traced.args))

        provenance.reset()
        plain = jaxpr_text()
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_WINDOW", "4")
        monkeypatch.setenv("ESCALATOR_TPU_FLAP_MIN_ALTERNATIONS", "2")
        provenance.register_explainer(
            "armed", lambda key, groups: [{"group": 0}])
        try:
            _feed("armed", [1, -1] * 4)
            assert provenance.FLAPS.flaps >= 1
            armed = jaxpr_text()
        finally:
            provenance.unregister_explainer("armed")
        assert armed == plain, f"{name}: jaxpr changed under provenance"


# ---------------------------------------------------------------------------
# hook overhead: the steady-tick feed is sub-quarter-millisecond
# ---------------------------------------------------------------------------


def test_history_feed_overhead_under_quarter_millisecond():
    """The acceptance bound on the root-complete hook's provenance leg: a
    steady tick (no delta, no status change — the prefiltered path every
    production tick takes) stages + ingests in well under 0.25 ms."""
    status = np.zeros(64, np.int64)
    delta = np.zeros(64, np.int64)
    for i in range(50):   # warm the ring + the config memo
        provenance.stage("overhead", status, delta, tick=i + 1)
    iters = 400
    t0 = time.perf_counter()
    for i in range(iters):
        provenance.stage("overhead", status, delta, tick=100 + i)
    per_tick = (time.perf_counter() - t0) / iters
    assert per_tick < 0.25e-3, f"{per_tick * 1e3:.3f} ms per staged tick"


# ---------------------------------------------------------------------------
# the traced explain path: 30-tick randomized parity soak + replay
# ---------------------------------------------------------------------------


@pytest.fixture
def _input_log_hygiene():
    from escalator_tpu.observability import replay

    replay.INPUT_LOG.set_enabled(False)
    replay.INPUT_LOG.clear()
    yield
    replay.INPUT_LOG.set_enabled(False)
    replay.INPUT_LOG.clear()


def _soak_tick(host, cache, inc, rng, t):
    """One randomized churn tick: pod resource churn plus taint/cordon
    flips on live nodes (a tainted node with pods IS the drain
    transition), then the incremental ordered decide."""
    P = host.pods.valid.shape[0]
    N = host.nodes.valid.shape[0]
    pidx = np.unique(rng.integers(0, P, 5))
    host.pods.cpu_milli[pidx] = rng.integers(100, 8000, len(pidx))
    host.pods.mem_bytes[pidx] = rng.integers(1 << 20, 1 << 34, len(pidx))
    nidx = np.unique(rng.integers(0, N, 3))
    host.nodes.tainted[nidx] = ~host.nodes.tainted[nidx]
    host.nodes.cordoned[nidx[:1]] = ~host.nodes.cordoned[nidx[:1]]
    inc.apply_gathered(cache.gather_deltas(pidx.astype(np.int64),
                                           nidx.astype(np.int64)))
    return inc.decide(NOW + 60 * t, tainted_any=True)


def _assert_explained_parity(docs, out, t):
    """The acceptance contract, per tick: every clean group's document is
    bit-equal to the committed columns, no cross-check finding survived,
    and the named threshold branch is exactly the arm its gates fired."""
    status = np.asarray(out.status)
    delta = np.asarray(out.nodes_delta)
    cpu = np.asarray(out.cpu_percent)
    mem = np.asarray(out.mem_percent)
    assert len(docs) == status.shape[0]
    arm = {"gate_down_fast": "scale_down_fast",
           "gate_down_slow": "scale_down_slow",
           "gate_scale_up": "scale_up"}
    for d in docs:
        assert "mismatches" not in d, f"tick {t}: {d}"
        fired = [k for k in ("gate_down_fast", "gate_down_slow",
                             "gate_scale_up") if d["gates"][k]]
        assert d["threshold_branch"] == (
            arm[fired[0]] if fired else "hold"), f"tick {t}: {d}"
        if d["stale"]:
            continue   # a pending delta: columns legitimately behind
        g = d["group"]
        assert d["status"] == int(status[g]), f"tick {t} group {g}"
        assert d["nodes_delta"] == int(delta[g]), f"tick {t} group {g}"
        assert np.float64(d["terms"]["cpu_percent"]).tobytes() \
            == cpu[g].tobytes(), f"tick {t} group {g}: cpu bits"
        assert np.float64(d["terms"]["mem_percent"]).tobytes() \
            == mem[g].tobytes(), f"tick {t} group {g}: mem bits"


def test_thirty_tick_randomized_explain_parity_and_replay(
        tmp_path, capsys, _input_log_hygiene):
    """The tentpole soak: 30 randomized ticks (pod churn, taint/cordon
    flips, drain transitions) with every tick's explanation bit-cross-
    checked against the committed columns — then the SAME assertion
    offline: ``debug-explain --replay`` re-executes the recorded ring
    from a mid-run snapshot and must print byte-identical explanations."""
    from escalator_tpu.analysis.registry import representative_cluster
    from escalator_tpu.cli import main
    from escalator_tpu.observability import RECORDER, replay
    from escalator_tpu.ops import snapshot as snaplib
    from escalator_tpu.ops.device_state import (
        DeviceClusterCache,
        IncrementalDecider,
    )

    host = representative_cluster(seed=1923)
    cache = DeviceClusterCache(host)
    inc = IncrementalDecider(cache, refresh_every=0, background=False)
    rng = np.random.default_rng(1923)
    replay.INPUT_LOG.set_enabled(True)
    snap_path = None
    live_docs = None
    for t in range(30):
        if t == 27:
            leaves, meta = inc.snapshot_state()
            snap_path = snaplib.write_snapshot(
                str(tmp_path / "prov.snap"), leaves, meta)
        out, ordered = _soak_tick(host, cache, inc, rng, t)
        assert ordered
        live_docs = inc.explain()
        _assert_explained_parity(live_docs, out, t)
        # an incremental ordered tick attaches real scale-down victim
        # windows (tick 0 is the full-refresh decide: no persistent order
        # state to read them from yet)
        if t >= 1:
            assert any("scale_down_candidates" in d for d in live_docs), t
    assert provenance.mismatch_total() == 0
    replay.INPUT_LOG.set_enabled(False)
    entries = replay.INPUT_LOG.snapshot()
    assert len(entries) == 30

    # in-process replay: bit-identical explanations of the final state
    report = replay.replay_ring(entries, snapshot_path=snap_path,
                                explain=True)
    assert report["ok"], report["divergent"]
    assert report["replayed"] == 3 and report["explain_tick"] == 30
    canon_live = json.dumps(json.loads(json.dumps(live_docs)),
                            sort_keys=True)
    assert json.dumps(json.loads(json.dumps(report["explanations"])),
                      sort_keys=True) == canon_live

    # the CLI end: debug-explain --replay prints the same documents and
    # exits 0 (no divergence, no cross-check mismatch)
    dump_path = str(tmp_path / "ring.json")
    RECORDER.dump(dump_path, reason="test")
    rc = main(["debug-explain", "--replay", "--dump", dump_path,
               "--snapshot", snap_path, "--json"])
    cli_out = capsys.readouterr().out
    assert rc == 0
    cli_report = json.loads(cli_out)
    assert cli_report["ok"] and cli_report["replayed"] == 3
    assert json.dumps(cli_report["explanations"],
                      sort_keys=True) == canon_live
    # --replay without a snapshot is a usage error, not a traceback
    assert main(["debug-explain", "--replay", "--dump", dump_path]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# fleet end: explain_tenant parity, wildcard routing, cached provenance
# ---------------------------------------------------------------------------


class TestFleetExplain:
    @pytest.fixture(scope="class")
    def fleet(self):
        from escalator_tpu.analysis.registry import representative_cluster
        from escalator_tpu.fleet import DecideRequest, FleetEngine

        eng = FleetEngine(num_groups=6, pod_capacity=24, node_capacity=12,
                          max_tenants=2)
        clusters = {f"pv{i}": representative_cluster(6, 24, 12,
                                                     seed=640 + i)
                    for i in range(2)}
        results = {r.tenant_id: r for r in eng.step(
            [DecideRequest(t, c, NOW) for t, c in clusters.items()])}
        return eng, clusters, results

    def test_explain_tenant_matches_served_columns(self, fleet):
        eng, _clusters, results = fleet
        for tid, res in results.items():
            docs = eng.explain_tenant(tid)
            _assert_explained_parity(docs, res.arrays, tid)
        assert provenance.mismatch_total() == 0
        # groups filter returns exactly the requested rows
        docs = eng.explain_tenant("pv0", groups=[4, 2])
        assert [d["group"] for d in docs] == [4, 2]

    def test_unknown_tenant_raises_and_wildcard_shields(self, fleet):
        from escalator_tpu.fleet import TenantError

        eng, _c, _r = fleet
        with pytest.raises(TenantError, match="ghost"):
            eng.explain_tenant("ghost")
        # the dump worker's path: the wildcard explainer never raises
        assert eng._explain_for_provenance("ghost") is None
        assert provenance.explain_for("ghost") is None

    def test_wildcard_registration_routes_to_engine(self, fleet):
        eng, _c, _r = fleet
        via_registry = provenance.explain_for("pv1")
        direct = eng.explain_tenant("pv1")
        assert json.dumps(via_registry, sort_keys=True, default=str) \
            == json.dumps(direct, sort_keys=True, default=str)

    def test_cache_hit_stages_history_and_explains_consistently(
            self, fleet):
        from escalator_tpu.fleet import DecideRequest

        eng, clusters, _r = fleet
        # the same full frame at the same now: the digest fast path
        # answers from the cached columns — and must feed the SAME
        # history record a dispatch would have (satellite (c)'s unit end)
        res2 = eng.step([DecideRequest("pv0", clusters["pv0"], NOW)])[0]
        assert res2.cached and res2.batch_size == 0
        hist = provenance.HISTORY.history("pv0")
        assert hist, "cache hit staged no history record"
        assert hist[-1]["status"] == [int(s) for s in
                                      np.asarray(res2.arrays.status)]
        assert hist[-1]["nodes_delta"] == [
            int(d) for d in np.asarray(res2.arrays.nodes_delta)]
        docs = eng.explain_tenant("pv0")
        _assert_explained_parity(docs, res2.arrays, "cached pv0")
        assert provenance.mismatch_total() == 0


# ---------------------------------------------------------------------------
# CLI forensics: debug-explain --dump, debug-decision-diff, debug-journal
# ---------------------------------------------------------------------------


class TestCLIForensics:
    def _clean_docs(self):
        terms = _kernel_terms()
        return provenance.build_explanations(
            terms, committed=_committed_from(terms))

    def test_debug_explain_dump_exit_semantics(self, tmp_path, capsys):
        from escalator_tpu.cli import main

        docs = self._clean_docs()
        p = tmp_path / "docs.json"
        p.write_text(json.dumps(docs))
        assert main(["debug-explain", "--dump", str(p)]) == 0
        out = capsys.readouterr().out
        assert "group 0:" in out and "branch=" in out
        # --groups filters; --json carries the full documents
        assert main(["debug-explain", "--dump", str(p),
                     "--groups", "1,3", "--json"]) == 0
        shown = json.loads(capsys.readouterr().out)["explanations"]
        assert [d["group"] for d in shown] == [1, 3]
        # a surviving cross-check mismatch is exit 1 and rendered
        docs[0]["mismatches"] = [{"group": 0, "field": "status",
                                  "explained": 1, "committed": 0}]
        p.write_text(json.dumps(docs))
        assert main(["debug-explain", "--dump", str(p)]) == 1
        assert "MISMATCH" in capsys.readouterr().out
        # unreadable / carrier without docs -> exit 2
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert main(["debug-explain", "--dump", str(bad)]) == 2
        capsys.readouterr()

    def test_debug_explain_dump_multi_tenant_needs_tenant(
            self, tmp_path, capsys):
        from escalator_tpu.cli import main

        docs = self._clean_docs()
        p = tmp_path / "flight.json"
        p.write_text(json.dumps({"provenance": {"explanations": {
            "a": docs, "b": docs}}}))
        assert main(["debug-explain", "--dump", str(p)]) == 2
        assert "--tenant" in capsys.readouterr().err
        assert main(["debug-explain", "--dump", str(p),
                     "--tenant", "a"]) == 0
        assert main(["debug-explain", "--dump", str(p),
                     "--tenant", "zz"]) == 2
        capsys.readouterr()

    def test_flap_dump_is_a_first_class_carrier(self, tmp_path, capsys):
        """The forensics flow the watchdog sets up — "a reason=flap dump
        landed, explain/diff it" — must load the explanations the dump
        carries under its top-level ``flap`` section."""
        from escalator_tpu.cli import main

        docs = self._clean_docs()
        p = tmp_path / "escalator-tpu-flight-flap-0.json"
        p.write_text(json.dumps({
            "flight_recorder": True, "reason": "flap",
            "flap": {"key": "t0", "groups": [0], "explanations": docs}}))
        assert main(["debug-explain", "--dump", str(p)]) == 0
        assert "group 0:" in capsys.readouterr().out
        assert main(["debug-decision-diff", str(p), str(p)]) == 0
        capsys.readouterr()

    def test_debug_decision_diff_cli(self, tmp_path, capsys):
        from escalator_tpu.cli import main

        a = [_doc(terms={"max_percent": 60.0, "num_nodes": 3,
                         "num_untainted": 3}, config=_CFG,
                  gates={"gate_scale_up": False})]
        b = [_doc(status=4, delta=2, tb="scale_up",
                  terms={"max_percent": 80.0, "num_nodes": 3,
                         "num_untainted": 3}, config=_CFG,
                  gates={"gate_scale_up": True})]
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        pa.write_text(json.dumps(a))
        pb.write_text(json.dumps(b))
        # changed decision -> exit 1 (diff(1) semantics) + attribution
        assert main(["debug-decision-diff", str(pa), str(pb)]) == 1
        out = capsys.readouterr().out
        assert "because: max_percent crossed scale_up_threshold" in out
        assert "delta +0 -> +2" in out
        # identical sides -> exit 0
        assert main(["debug-decision-diff", str(pa), str(pa)]) == 0
        capsys.readouterr()
        # --json carries the structured diff document
        assert main(["debug-decision-diff", str(pa), str(pb),
                     "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["changed"][0]["term_deltas"]["max_percent"] == [60.0,
                                                                   80.0]
        # unreadable side -> exit 2
        assert main(["debug-decision-diff", str(pa),
                     str(tmp_path / "nope.json")]) == 2
        capsys.readouterr()

    def test_debug_journal_kind_comma_list_and_unknown_warning(
            self, tmp_path, capsys):
        from escalator_tpu.cli import main

        events = [
            {"seq": 1, "kind": "group-flap", "time_unix": 0, "key": "t0"},
            {"seq": 2, "kind": "explain-mismatch", "time_unix": 0},
            {"seq": 3, "kind": "slo-burn", "time_unix": 0},
        ]
        p = tmp_path / "flight.json"
        p.write_text(json.dumps({"journal": {
            "events": events, "total_recorded": 3, "capacity": 256}}))
        # one --kind flag, comma-separated list (blanks drop silently)
        rc = main(["debug-journal", "--dump", str(p),
                   "--kind", "group-flap,explain-mismatch,", "--json"])
        captured = capsys.readouterr()
        assert rc == 0 and captured.err == ""
        shown = json.loads(captured.out)["events"]
        assert [e["kind"] for e in shown] == ["group-flap",
                                              "explain-mismatch"]
        # a typo'd kind warns with the kinds actually present
        rc = main(["debug-journal", "--dump", str(p),
                   "--kind", "group-flop,slo-burn", "--json"])
        captured = capsys.readouterr()
        assert rc == 0
        assert "no events of kind(s) group-flop" in captured.err
        assert "kinds present:" in captured.err
        assert "group-flap" in captured.err
        assert [e["kind"] for e in json.loads(captured.out)["events"]] \
            == ["slo-burn"]
