"""What-if delta sweep + FFD bin-packing kernels (the capability extensions the
dense formulation buys, SURVEY.md §7 step 6)."""

import random

import numpy as np
import pytest

from escalator_tpu.core import semantics as sem
from escalator_tpu.core.arrays import pack_cluster
from escalator_tpu.ops import binpack, simulate
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)


def _cluster(num_pods=20, pod_cpu=500, node_cpu=1000, num_nodes=4, thr=70):
    cfg = sem.GroupConfig(
        min_nodes=0, max_nodes=1000, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=thr,
        slow_removal_rate=1, fast_removal_rate=2,
    )
    pods = build_test_pods(num_pods, PodOpts(cpu=[pod_cpu], mem=[10**8]))
    nodes = build_test_nodes(num_nodes, NodeOpts(cpu=node_cpu, mem=16 * 10**9))
    st = sem.GroupState()
    return pack_cluster([(pods, nodes, cfg, st)])


class TestDeltaSweep:
    def test_min_feasible_matches_manual(self):
        # req 10000m over cap 4000m; each added node brings 1000m (cached)
        # post(d) = 10000/(4000+1000d)*100 <= 70  =>  d >= 10.28 => 11
        cluster = _cluster()
        sweep = simulate.sweep_deltas_jit(cluster, 16)
        assert int(sweep.min_feasible_delta[0]) == 11
        assert not bool(sweep.feasible[0, 10])
        assert bool(sweep.feasible[0, 11])
        np.testing.assert_allclose(
            float(sweep.post_cpu_percent[0, 0]), 250.0
        )

    def test_delta_zero_feasible_when_under_threshold(self):
        cluster = _cluster(num_pods=2)
        sweep = simulate.sweep_deltas_jit(cluster, 4)
        assert int(sweep.min_feasible_delta[0]) == 0

    def test_infeasible_sentinel(self):
        cluster = _cluster(num_pods=1000)
        sweep = simulate.sweep_deltas_jit(cluster, 4)
        assert int(sweep.min_feasible_delta[0]) == 4  # sentinel = D

    def test_by_type_sweep(self):
        cluster = _cluster()
        post_cpu, post_mem, feasible, min_delta = simulate.sweep_deltas_by_type_jit(
            cluster,
            np.array([1000, 4000], np.int64),
            np.array([16 * 10**9, 64 * 10**9], np.int64),
            16,
        )
        assert min_delta.shape == (cluster.num_groups, 2)
        # bigger nodes -> fewer needed: 10000/(4000+4000d) <= 70% -> d >= 2.57 -> 3
        assert int(min_delta[0, 0]) == 11
        assert int(min_delta[0, 1]) == 3


class TestFFD:
    def _run_case(self, pods, bins, template, budget):
        G, P, M = 1, max(len(pods), 1), max(len(bins), 1)
        pod_cpu = np.zeros((G, P), np.int64)
        pod_mem = np.zeros((G, P), np.int64)
        pod_valid = np.zeros((G, P), bool)
        for i, (c, m) in enumerate(pods):
            pod_cpu[0, i], pod_mem[0, i], pod_valid[0, i] = c, m, True
        bin_cpu = np.zeros((G, M), np.int64)
        bin_mem = np.zeros((G, M), np.int64)
        bin_valid = np.zeros((G, M), bool)
        for i, (c, m) in enumerate(bins):
            bin_cpu[0, i], bin_mem[0, i], bin_valid[0, i] = c, m, True
        out = binpack.ffd_pack(
            pod_cpu, pod_mem, pod_valid, bin_cpu, bin_mem, bin_valid,
            np.array([template[0]], np.int64), np.array([template[1]], np.int64),
            new_bin_budget=budget,
        )
        want_assign, want_new, want_unplaced = binpack.ffd_pack_reference(
            pods, bins, template, budget
        )
        got_assign = [int(a) for a in np.asarray(out.assignment[0])[: len(pods)]]
        assert got_assign == want_assign
        assert int(out.new_nodes_needed[0]) == want_new
        assert int(out.unplaced[0]) == want_unplaced
        return out

    def test_simple_overflow_to_new_nodes(self):
        # 2 nodes with 1000m free each; 5 pods of 600m -> 2 placed, 3 new nodes
        self._run_case(
            pods=[(600, 10**8)] * 5,
            bins=[(1000, 10**9), (1000, 10**9)],
            template=(1000, 10**9),
            budget=4,
        )

    def test_heterogeneous_bins(self):
        # big pod only fits the big node; smalls fill the rest
        self._run_case(
            pods=[(3000, 10**8), (500, 10**8), (500, 10**8), (900, 10**8)],
            bins=[(1000, 10**9), (4000, 10**9)],
            template=(1000, 10**9),
            budget=2,
        )

    def test_mem_constrained(self):
        self._run_case(
            pods=[(100, 8 * 10**8), (100, 8 * 10**8), (100, 8 * 10**8)],
            bins=[(4000, 10**9)],
            template=(4000, 10**9),
            budget=3,
        )

    def test_unplaceable_pod(self):
        # pod bigger than any bin incl. template -> unplaced
        self._run_case(
            pods=[(9000, 10**8)],
            bins=[(1000, 10**9)],
            template=(1000, 10**9),
            budget=2,
        )

    def test_identical_pods_take_the_run_path(self):
        """Many identical pods: the histogram prepass must collapse them to
        runs (the blocked scan) and stay bit-exact — including the budget
        cap and the partially-filled-bin boundary inside a run."""
        pods = [(600, 10**8)] * 17 + [(300, 5 * 10**7)] * 9
        stats = binpack.pack_compression_stats(
            np.array([[c for c, _ in pods]], np.int64),
            np.array([[m for _, m in pods]], np.int64),
            np.ones((1, len(pods)), bool),
            np.array([1000], np.int64), np.array([10**9], np.int64),
        )
        assert stats["path"] == "runs"
        self._run_case(
            pods=pods,
            bins=[(1000, 10**9), (700, 10**9), (2500, 10**9)],
            template=(1000, 10**9),
            budget=3,
        )

    def test_single_pod_bins(self):
        """Bins that hold exactly one pod each: every take is 0/1, the run
        fill must advance bin-by-bin."""
        self._run_case(
            pods=[(900, 10**8)] * 6,
            bins=[(1000, 10**9)] * 4,
            template=(1000, 10**9),
            budget=1,
        )

    def test_zero_request_pods(self):
        """Zero-request pods fit every valid bin (division-free capacity is
        unbounded); all must land in the first bin, as the golden model
        places them."""
        self._run_case(
            pods=[(0, 0)] * 5 + [(500, 10**8)],
            bins=[(1000, 10**9), (400, 10**9)],
            template=(1000, 10**9),
            budget=2,
        )

    def test_values_beyond_trim_range_stay_exact(self):
        """cpu above the f32-exact bound (2**24) must force the int64 scan
        program; results still match the golden model bit-for-bit."""
        big = 1 << 30
        self._run_case(
            pods=[(big, 10**8), (big // 2, 10**8), (7, 10**8)],
            bins=[(big + 5, 10**9)],
            template=(big, 10**9),
            budget=2,
        )

    def test_compression_stats_paths(self):
        rng = np.random.default_rng(0)
        G, P = 4, 32
        pv = np.ones((G, P), bool)
        tc = np.full(G, 4000, np.int64)
        tm = np.full(G, 16 * 10**9, np.int64)
        # distinct-heavy: every pod unique -> per-pod scan
        pc = (np.arange(G * P, dtype=np.int64).reshape(G, P) + 1) * 7
        pm = (np.arange(G * P, dtype=np.int64).reshape(G, P) + 1) * 11
        assert binpack.pack_compression_stats(pc, pm, pv, tc, tm)["path"] == "pods"
        # one replica shape -> run scan with a tiny step count
        stats = binpack.pack_compression_stats(
            np.full((G, P), 500, np.int64), np.full((G, P), 10**9, np.int64),
            pv, tc, tm,
        )
        assert stats["path"] == "runs" and stats["scan_steps"] <= 4

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_against_reference(self, seed):
        rng = random.Random(seed)
        G = 8
        P, M, B = 24, 6, 8
        pod_cpu = np.zeros((G, P), np.int64)
        pod_mem = np.zeros((G, P), np.int64)
        pod_valid = np.zeros((G, P), bool)
        bin_cpu = np.zeros((G, M), np.int64)
        bin_mem = np.zeros((G, M), np.int64)
        bin_valid = np.zeros((G, M), bool)
        tmpl_cpu = np.zeros(G, np.int64)
        tmpl_mem = np.zeros(G, np.int64)
        cases = []
        for g in range(G):
            np_ = rng.randint(0, P)
            nb = rng.randint(0, M)
            pods = [
                (rng.choice([100, 250, 500, 1000, 2000]),
                 rng.choice([10**8, 5 * 10**8, 10**9]))
                for _ in range(np_)
            ]
            bins = [
                (rng.choice([1000, 2000, 4000]), rng.choice([10**9, 4 * 10**9]))
                for _ in range(nb)
            ]
            tmpl = (rng.choice([1000, 4000]), rng.choice([10**9, 8 * 10**9]))
            cases.append((pods, bins, tmpl))
            for i, (c, m) in enumerate(pods):
                pod_cpu[g, i], pod_mem[g, i], pod_valid[g, i] = c, m, True
            for i, (c, m) in enumerate(bins):
                bin_cpu[g, i], bin_mem[g, i], bin_valid[g, i] = c, m, True
            tmpl_cpu[g], tmpl_mem[g] = tmpl

        out = binpack.ffd_pack(
            pod_cpu, pod_mem, pod_valid, bin_cpu, bin_mem, bin_valid,
            tmpl_cpu, tmpl_mem, new_bin_budget=B,
        )
        for g, (pods, bins, tmpl) in enumerate(cases):
            want_assign, want_new, want_unplaced = binpack.ffd_pack_reference(
                pods, bins, tmpl, B
            )
            # virtual bin indices shift by (M - len(bins)) padding offset
            got = []
            for a in np.asarray(out.assignment[g])[: len(pods)]:
                a = int(a)
                if a >= M:
                    a = a - M + len(bins)
                got.append(a)
            assert got == want_assign, f"group {g}"
            assert int(out.new_nodes_needed[g]) == want_new, f"group {g}"
            assert int(out.unplaced[g]) == want_unplaced, f"group {g}"
