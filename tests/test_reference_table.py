"""Row-for-row mirror of the reference's canonical controller table.

/root/reference/pkg/controller/controller_scale_node_group_test.go:203-551
(TestScaleNodeGroup) pins EXACT node deltas for a fixed menu of cluster
shapes, then has the mock cloud fulfil the delta and asserts a re-run
converges to zero. This file reproduces every decision row with the same
numbers and the same two-phase structure, across every backend, so the
parity claim is checkable against the reference line by line rather than
only property-by-property (tests/test_semantics.py holds the closed-loop
property; this holds the reference's own expected values).

Mapping notes:
- The reference builder OMITS a resource from node capacity when the option
  is negative (/root/reference/pkg/test/builder.go:135-140 ``opts.CPU >= 0``),
  so its "invalid usage/requests" rows reduce to zero capacity; they are
  encoded here with the effective zero values.
- Rows whose NodeGroupOptions leave fields at Go zero values (taint
  thresholds, removal rates) are mirrored with explicit zeros — with our
  default taint_lower=30 the "no need to scale up" row (25% cpu) would
  taint-scale-down instead of no-op, which is NOT what the reference row
  asserts.
- The two lister-error rows are controller-plumbing, covered by
  tests/test_controller.py::test_lister_error_skips_group; the node-lister
  flavor is added here.
"""

import pytest

from escalator_tpu.controller import controller as ctl
from escalator_tpu.k8s.client import InMemoryKubernetesClient
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)
from escalator_tpu.testsupport.cloud_provider import (
    MockBuilder,
    MockCloudProvider,
    MockNodeGroup,
)
from escalator_tpu.utils.clock import MockClock
from tests.test_controller import LABEL_KEY, LABEL_VALUE, World, make_opts
from tests.test_controller import backend  # noqa: F401  (pytest fixture, used by name)


def table_opts(min_nodes, max_nodes, scale_up):
    """NodeGroupOptions as the reference table builds them: only name/group/
    min/max/threshold set, everything else at the Go zero value."""
    return make_opts(
        min_nodes=min_nodes,
        max_nodes=max_nodes,
        scale_up_threshold_percent=scale_up,
        taint_lower_capacity_threshold_percent=0,
        taint_upper_capacity_threshold_percent=0,
        slow_node_removal_rate=0,
        fast_node_removal_rate=0,
        # Go zero value: no cooldown, so the fulfilled re-run is not LOCKED
        scale_up_cool_down_period="0s",
    )


# (name, (n_nodes, node_cpu, node_mem), (n_pods, pod_cpu, pod_mem),
#  (min, max, scale_up_threshold), expected_delta, expected_log_fragment)
ROWS = [
    ("100pct_cpu_50thr", (10, 2000, 8000), (40, 500, 1000), (5, 100, 50), 10, None),
    ("100pct_mem_50thr", (10, 2000, 8000), (40, 100, 2000), (5, 100, 50), 10, None),
    ("100pct_cpu_70thr", (10, 2000, 8000), (40, 500, 1000), (5, 100, 70), 5, None),
    ("150pct_cpu_70thr", (10, 2000, 8000), (60, 500, 1000), (5, 100, 70), 12, None),
    ("no_nodes_no_pods", (0, 0, 0), (0, 0, 0), (0, 10, 70), 0, None),
    ("scale_up_from_0_node", (0, 1000, 10000), (1, 500, 1000), (0, 10, 70), 1, None),
    ("below_minimum", (1, 0, 0), (0, 0, 0), (5, 0, 0), 0, "less than minimum"),
    ("above_maximum", (10, 0, 0), (0, 0, 0), (0, 5, 0), 0, "larger than maximum"),
    # reference rows 9-11 all reduce to this one row: its builder OMITS a
    # resource when the option is negative, so the two "invalid
    # usage/requests" rows are the zero-capacity row under other names
    ("div_zero_zero_capacity", (10, 0, 0), (5, 0, 0), (1, 100, 0), 0,
     "cannot divide by zero"),
    ("no_need_to_scale_up", (10, 2000, 8000), (5, 1000, 2000), (1, 100, 70), 0, None),
    ("scale_up_test", (10, 1500, 5000), (100, 500, 600), (5, 100, 70), 38, None),
]


@pytest.mark.parametrize("row", ROWS, ids=[r[0] for r in ROWS])
def test_scale_node_group_table(row, backend, caplog):
    name, (nn, ncpu, nmem), (np_, pcpu, pmem), (mn, mx, thr), want, log_frag = row
    nodes = build_test_nodes(nn, NodeOpts(cpu=ncpu, mem=nmem))
    pods = build_test_pods(np_, PodOpts(
        cpu=[pcpu], mem=[pmem],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE,
    )) if np_ else []
    w = World(table_opts(mn, mx, thr), nodes=nodes, pods=pods, backend=backend)

    with caplog.at_level("WARNING"):
        w.tick()

    assert w.state.scale_delta == want, name
    if log_frag is not None:
        assert any(log_frag in r.message for r in caplog.records), (
            f"{name}: expected log containing {log_frag!r}"
        )
    if want <= 0:
        assert w.group.target_size() == nn
        return

    # the reference's second phase: provider moved by exactly the delta, the
    # cloud fulfils it, and a re-run needs nothing more
    assert w.group.target_size() == nn + want, name
    w.simulate_cloud_fills_nodes(ncpu, nmem)
    w.tick()
    assert w.state.scale_delta == 0, f"{name}: second run must converge to 0"


# Mirror of TestScaleNodeGroup_MultipleRuns
# (controller_scale_node_group_test.go:553-776): first-run delta pinned, then
# N further ticks with the clock advancing — tainted nodes age past soft
# grace and get reaped (provider target AND size shrink by the delta), or the
# cooldown lock holds a from-zero scale-up at exactly one buy. The reference
# advances by exactly the grace/cooldown period and relies on Go clock tie
# behavior; here the advances are unambiguous (61s per run; 59s for the
# locked row) because the tie is incidental, not semantics.
#
# (name, n_nodes, (n_pods, pod_cpu, pod_mem), opts overrides, cached?,
#  runs, advance_per_run_sec, first_delta, final_target)
MULTI_ROWS = [
    # removal rows: the reference leaves ScaleUpCoolDownPeriod at the Go zero
    # value (no lock is ever taken on a scale-down, but mirror it anyway)
    ("fast_removal_to_min", 10, (0, 0, 0),
     dict(min_nodes=5, scale_up_cool_down_period="0s"), False, 1, 61, -4, 6),
    ("slow_removal", 10, (10, 1000, 1000),
     dict(min_nodes=5, soft_delete_grace_period="5m",
          scale_up_cool_down_period="0s", taint_effect="NoSchedule"),
     False, 5, 61, -2, 8),
    ("fast_removal_to_zero", 4, (0, 0, 0),
     dict(min_nodes=0, scale_up_cool_down_period="0s"), False, 1, 61, -4, 0),
    ("from_zero_no_cache_cooldown_holds", 0, (40, 200, 800),
     dict(min_nodes=0), False, 1, 59, 1, 1),
    ("from_zero_with_cache", 0, (40, 200, 800), dict(min_nodes=0), True,
     1, 59, 6, 6),
]

NODE_CPU, NODE_MEM = 2000, 8000


@pytest.mark.parametrize("row", MULTI_ROWS, ids=[r[0] for r in MULTI_ROWS])
def test_scale_node_group_multiple_runs(row, backend):
    (name, nn, (np_, pcpu, pmem), over, cached, runs, step, first_delta,
     final_target) = row
    kw = dict(
        max_nodes=100,
        scale_up_threshold_percent=70,
        taint_lower_capacity_threshold_percent=40,
        taint_upper_capacity_threshold_percent=60,
        fast_node_removal_rate=4,
        slow_node_removal_rate=2,
        soft_delete_grace_period="1m",
        hard_delete_grace_period="15m",
        scale_up_cool_down_period="1m",
        taint_effect="NoExecute",
    )
    kw.update(over)
    opts = make_opts(**kw)
    nodes = build_test_nodes(nn, NodeOpts(cpu=NODE_CPU, mem=NODE_MEM))
    pods = build_test_pods(np_, PodOpts(
        cpu=[pcpu], mem=[pmem],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE,
    )) if np_ else []
    w = World(opts, nodes=nodes, pods=pods, backend=backend)
    if cached:
        # the reference injects cached per-node allocatable directly
        # (controller_scale_node_group_test.go:735-740)
        w.state.kernel_state.cached_cpu_milli = NODE_CPU
        w.state.kernel_state.cached_mem_bytes = NODE_MEM

    w.tick()
    assert w.state.scale_delta == first_delta, name

    for _ in range(runs):
        w.clock.advance(step)
        w.tick()

    assert w.group.target_size() == final_target, name
    assert w.group.size() == final_target, name


def test_untaint_to_min_nodes(backend):
    """TestUntaintNodeGroupMinNodes (controller_scale_node_group_test.go:75-133):
    10 tainted / 0 untainted with min=10 — the forced-min scale-up is satisfied
    entirely by untainting; the provider is never asked for nodes."""
    nodes = build_test_nodes(10, NodeOpts(cpu=1000, mem=1000, tainted=True,
                                          taint_time_sec=1))
    pods = build_test_pods(10, PodOpts(
        cpu=[1000], mem=[1000],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=10, max_nodes=20,
                        scale_up_threshold_percent=100),
              nodes=nodes, pods=pods, backend=backend)
    w.tick()
    assert len(w.tainted_nodes()) == 0
    assert len(w.client.list_nodes()) == 10
    assert w.group.increase_calls == []
    assert w.group.target_size() == 10


def test_untaint_at_max_nodes(backend):
    """TestUntaintNodeGroupMaxNodes (controller_scale_node_group_test.go:137-202):
    at max size with 5 tainted + 5 untainted and 200% pressure — untainting is
    allowed (it adds no nodes) and covers the delta up to max; the provider
    increase is clamped at max and never called."""
    nodes = (build_test_nodes(5, NodeOpts(cpu=1000, mem=1000, tainted=True,
                                          taint_time_sec=1))
             + build_test_nodes(5, NodeOpts(cpu=1000, mem=1000)))
    pods = build_test_pods(10, PodOpts(
        cpu=[1000], mem=[1000],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=2, max_nodes=10),
              nodes=nodes, pods=pods, backend=backend)
    w.tick()
    assert len(w.tainted_nodes()) == 0
    assert w.group.increase_calls == []
    assert w.group.target_size() == 10


def test_node_lister_error_skips_group(backend):
    """Reference row 'lister not being able to list nodes' (:427-450):
    a failing NODE listing must leave the group untouched, not crash the run."""
    if not hasattr(backend, "decide"):
        pytest.skip("event-driven backend has no lister path")

    class FailingClient(InMemoryKubernetesClient):
        fail = False

        def list_nodes(self):
            if self.fail:
                raise RuntimeError("unable to list nodes")
            return super().list_nodes()

    nodes = build_test_nodes(10, NodeOpts(cpu=2000, mem=8000))
    for n in nodes:
        n.labels = {LABEL_KEY: LABEL_VALUE}
    client = FailingClient(nodes=nodes)
    provider = MockCloudProvider()
    provider.register_node_group(MockNodeGroup("buildeng-asg", "buildeng", 1, 100, 10))
    c = ctl.Controller(ctl.Opts(
        client=client, node_groups=[make_opts()],
        cloud_provider_builder=MockBuilder(provider), backend=backend,
        clock=MockClock(),
    ))
    client.fail = True
    c.run_once()  # must not raise
    assert c.node_groups["buildeng"].scale_delta == 0
