"""Group-block-sharded ordering tail (ops.order_tail): window-exact parity.

The pod-axis decider's busy-tick path (podaxis.make_podaxis_decider with a
``node_blocks`` map) replaces the replicated full-[N] combined sort with
per-device block sorts + a psum reassembly. The contract is the kernel's
documented one: every NON-order field bit-identical to the single-device
kernel, and both ordering permutations bit-identical INSIDE every per-group
offset window (the only regions consumers may read; the class-2 region
beyond the windows is explicitly unspecified — see ops/order_tail.py).
Adversarial layouts from the round-6 issue: group-interleaved node slots,
empty groups, all-tainted clusters, a single giant group (S-1 blocks empty,
the lax.cond skip path), emptiest-first victim keys, and high-water-padded
block maps.
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from escalator_tpu.core.arrays import NO_TAINT_TIME, NodeArrays  # noqa: E402
from escalator_tpu.ops import kernel, order_tail  # noqa: E402
from escalator_tpu.parallel import podaxis  # noqa: E402
from escalator_tpu.parallel.mesh import make_hybrid_mesh, make_mesh  # noqa: E402
from tests.test_podaxis import ALL_FIELDS, NOW, _random_cluster  # noqa: E402

ORDER_FIELDS = ("scale_down_order", "untaint_order")
G_DEFAULT = 16


def _assert_window_parity(single, sharded, G):
    """Non-order fields bit-equal; order fields bit-equal inside every
    window; both order outputs remain valid permutations of [N]."""
    for f in ALL_FIELDS:
        if f in ORDER_FIELDS:
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(single, f)), np.asarray(getattr(sharded, f)),
            err_msg=f,
        )
    u_off = np.asarray(single.untainted_offsets)
    t_off = np.asarray(single.tainted_offsets)
    down_s, down_b = (np.asarray(o.scale_down_order) for o in (single, sharded))
    up_s, up_b = (np.asarray(o.untaint_order) for o in (single, sharded))
    for g in range(G):
        np.testing.assert_array_equal(
            down_s[u_off[g]:u_off[g + 1]], down_b[u_off[g]:u_off[g + 1]],
            err_msg=f"scale-down window g={g}",
        )
        np.testing.assert_array_equal(
            up_s[t_off[g]:t_off[g + 1]], up_b[t_off[g]:t_off[g + 1]],
            err_msg=f"untaint window g={g}",
        )
    N = down_s.shape[0]
    assert sorted(down_b.tolist()) == list(range(N))
    assert sorted(up_b.tolist()) == list(range(N))


def _run_sharded(cluster, G, mesh=None, block_pad=None):
    mesh = mesh if mesh is not None else make_mesh()
    S = int(mesh.devices.size)
    placed = podaxis.place(podaxis.pad_pods_for_mesh(cluster, mesh), mesh)
    blocks = order_tail.assign_order_blocks(
        cluster.nodes.group, cluster.nodes.valid, S, num_groups=G)
    if block_pad is not None:
        blocks = order_tail.pad_order_blocks(blocks, block_pad)
    return podaxis.make_podaxis_decider(mesh)(placed, NOW, blocks)


@pytest.mark.parametrize("giant_group", [False, True])
@pytest.mark.parametrize("P", [1000, 1001])  # 1001 exercises pod padding
def test_sharded_tail_window_parity(P, giant_group):
    """Group-interleaved node slots (the _random_cluster default) with and
    without one dominant giant group."""
    rng = np.random.default_rng(P + int(giant_group))
    cluster = _random_cluster(rng, G=G_DEFAULT, P=P, N=200,
                              giant_group=giant_group)
    single = kernel.decide_jit(jax.device_put(cluster), NOW)
    sharded = _run_sharded(cluster, G_DEFAULT)
    _assert_window_parity(single, sharded, G_DEFAULT)


def test_single_group_all_nodes_one_block():
    """ONE group owns every node: S-1 blocks are pure padding and take the
    cond skip branch; group 0's block must still sort bit-exactly."""
    rng = np.random.default_rng(3)
    cluster = _random_cluster(rng, G=1, P=512, N=160)
    single = kernel.decide_jit(jax.device_put(cluster), NOW)
    sharded = _run_sharded(cluster, 1)
    _assert_window_parity(single, sharded, 1)
    blocks = order_tail.assign_order_blocks(
        cluster.nodes.group, cluster.nodes.valid, 8, num_groups=1)
    # the partition really is degenerate: one live block, seven empty
    assert (blocks[1:] < 0).all() and (blocks[0] >= 0).all()


def test_empty_groups_and_all_tainted():
    rng = np.random.default_rng(4)
    cluster = _random_cluster(rng, G=G_DEFAULT, P=1000, N=200)
    n = cluster.nodes
    # groups 3..7 own no nodes (shift their nodes to group 8+); all nodes
    # tainted -> every scale-down window empty, untaint windows carry all
    group = np.asarray(n.group).copy()
    group[(group >= 3) & (group <= 7)] = 8
    cluster.nodes = NodeArrays(
        group=group, cpu_milli=n.cpu_milli, mem_bytes=n.mem_bytes,
        creation_ns=n.creation_ns,
        tainted=np.ones_like(np.asarray(n.tainted)),
        cordoned=np.zeros_like(np.asarray(n.cordoned)),
        no_delete=n.no_delete,
        taint_time_sec=np.where(
            np.asarray(n.valid), int(NOW) - 100, NO_TAINT_TIME
        ).astype(np.int64),
        valid=n.valid,
    )
    single = kernel.decide_jit(jax.device_put(cluster), NOW)
    sharded = _run_sharded(cluster, G_DEFAULT)
    _assert_window_parity(single, sharded, G_DEFAULT)


def test_emptiest_first_victim_keys_cross_blocks():
    """emptiest_first groups rank victims by pods-remaining before age; the
    sharded tail must thread the victim-primary key through its block sorts."""
    rng = np.random.default_rng(5)
    cluster = _random_cluster(rng, G=8, P=2048, N=128)
    cluster.groups.emptiest = np.ones_like(np.asarray(cluster.groups.emptiest))
    single = kernel.decide_jit(jax.device_put(cluster), NOW)
    sharded = _run_sharded(cluster, 8)
    _assert_window_parity(single, sharded, 8)


def test_high_water_padded_block_map():
    """pad_order_blocks widens the lane axis with -1 (the backend's
    high-water jit-cache policy); results must not change."""
    rng = np.random.default_rng(6)
    cluster = _random_cluster(rng, G=G_DEFAULT, P=1000, N=200)
    single = kernel.decide_jit(jax.device_put(cluster), NOW)
    sharded = _run_sharded(cluster, G_DEFAULT, block_pad=512)
    _assert_window_parity(single, sharded, G_DEFAULT)


def test_hybrid_mesh_tail():
    """The (dcn, ici) two-axis mesh: block axis spans both axes; the psum
    reassembly runs staged over each."""
    rng = np.random.default_rng(7)
    cluster = _random_cluster(rng, G=8, P=1003, N=120, giant_group=True)
    single = kernel.decide_jit(jax.device_put(cluster), NOW)
    hybrid = make_hybrid_mesh(num_hosts=2)
    sharded = _run_sharded(cluster, 8, mesh=hybrid)
    _assert_window_parity(single, sharded, 8)


def test_assign_order_blocks_properties():
    """Contiguous ascending group ranges, every lane in exactly one block,
    invalid lanes riding with group 0."""
    rng = np.random.default_rng(8)
    N, G, S = 333, 12, 8
    group = rng.integers(0, G, N).astype(np.int32)
    valid = rng.random(N) < 0.9
    blocks = order_tail.assign_order_blocks(group, valid, S, num_groups=G)
    assert blocks.shape[0] == S
    lanes = blocks[blocks >= 0]
    assert sorted(lanes.tolist()) == list(range(N))
    key_group = np.where(valid, group, 0)
    # group ranges ascend block to block and never straddle blocks
    seen_groups = [np.unique(key_group[blocks[b][blocks[b] >= 0]])
                   for b in range(S)]
    flat = [g for arr in seen_groups for g in arr]
    assert flat == sorted(flat)
    for a in range(S):
        for b in range(a + 1, S):
            assert not set(seen_groups[a]) & set(seen_groups[b])


def test_sharded_tail_is_block_sized_in_the_lowering():
    """The busy-tick regression lock: the ordered pod-axis program with a
    block map contains exactly ONE sort, and that sort runs on [Nb] block
    lanes — NOT on the full replicated [N] node axis (round 5's 218 ms
    cfg8 tail). The light program stays sort-free."""
    import re

    rng = np.random.default_rng(9)
    N, Nb = 256, 32  # balanced 8-block partition: Nb = N / 8
    cluster = _random_cluster(rng, G=8, P=512, N=N)
    # balanced layout so every block gets exactly N // 8 lanes
    cluster.nodes.group = np.sort(np.arange(N) % 8).astype(np.int32)
    cluster.nodes.valid = np.ones(N, bool)
    mesh = make_mesh()
    blocks = order_tail.assign_order_blocks(
        cluster.nodes.group, cluster.nodes.valid, 8, num_groups=8)
    assert blocks.shape == (8, Nb)
    placed = podaxis.place(podaxis.pad_pods_for_mesh(cluster, mesh), mesh)

    ordered = podaxis.make_podaxis_decider(mesh)
    txt = ordered.lower(placed, NOW, blocks).as_text()
    assert len(re.findall(r"stablehlo\.sort", txt)) == 1
    # the sort's operand tuple (after its comparator region closes) must be
    # block-sized, not node-axis-sized
    m = re.search(r"stablehlo\.sort.*?\}\) : \(([^)]*)\)", txt, flags=re.S)
    assert m, "sort operand signature not found"
    sig = m.group(1)
    assert f"tensor<{Nb}x" in sig, sig
    assert f"tensor<{N}x" not in sig, sig

    light = podaxis.make_podaxis_decider(mesh, with_orders=False)
    txt_light = light.lower(placed, NOW).as_text()
    assert len(re.findall(r"stablehlo\.sort", txt_light)) == 0


# ---------------------------------------------------------------------------
# Incremental order state (round 10): key recompute + rank-repair merge
# ---------------------------------------------------------------------------

def _repair_world(rng, N, G=8):
    """Random key columns with heavy tie pressure (small value ranges force
    the lane-index tie-break to matter) — the repair merge must reproduce
    the full sort under maximal ambiguity, not just on distinct keys."""
    major = rng.integers(0, 3 * G, N).astype(np.int64)
    k1 = rng.integers(-4, 4, N).astype(np.int64)
    k2 = rng.integers(0, 3, N).astype(np.int64)
    return major, k1, k2


@pytest.mark.parametrize("N", [5, 64, 257])
@pytest.mark.parametrize("dirty_frac", [0.0, 0.02, 0.3, 1.0])
def test_order_repair_matches_full_sort(N, dirty_frac):
    """order_repair_jit == order_sort_jit bit-for-bit, across sizes and
    dirty fractions (0 = an all-pad bucket, 1.0 = every lane dirty — the
    clean subsequence is empty), under key-tie pressure."""
    import jax.numpy as jnp

    rng = np.random.default_rng(N * 1000 + int(dirty_frac * 100))
    major, k1, k2 = _repair_world(rng, N)
    perm_old = np.asarray(order_tail.order_sort_jit(
        jnp.asarray(major), jnp.asarray(k1), jnp.asarray(k2)))

    dirty = rng.random(N) < dirty_frac
    nm, n1, n2 = major.copy(), k1.copy(), k2.copy()
    nm[dirty] = rng.integers(0, 24, int(dirty.sum()))
    n1[dirty] = rng.integers(-4, 4, int(dirty.sum()))
    # mask from the ACTUAL key diff (a mutated lane may land on its old
    # keys — then it is NOT dirty, exactly as order_update_jit's diff
    # computes)
    changed = (nm != major) | (n1 != k1) | (n2 != k2)
    idx = kernel.dirty_indices(changed)

    got = np.asarray(order_tail.order_repair_jit(
        jnp.asarray(perm_old), jnp.asarray(major), jnp.asarray(k1),
        jnp.asarray(k2), jnp.asarray(nm), jnp.asarray(n1),
        jnp.asarray(n2), jnp.asarray(idx)))
    want = np.asarray(order_tail.order_sort_jit(
        jnp.asarray(nm), jnp.asarray(n1), jnp.asarray(n2)))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("bucket", [4, 64])
def test_order_update_fused_program(bucket):
    """order_update_jit — the fused keys + diff + compaction + merge + roll
    program — returns the recomputed keys, the TRUE changed-lane count, and
    (when the bucket holds every changed lane) the exact full-sort
    permutation with its scale-down roll; on bucket overflow the count
    exceeds ``bucket``, the caller's contract for discarding the perm."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    G, P, N = 8, 128, 96
    cluster = _random_cluster(rng, G, P, N)
    cluster.groups.emptiest[:3] = True
    aggs = kernel.compute_aggregates_jit(jax.device_put(cluster))
    cols = (jnp.asarray(cluster.groups.emptiest),
            jnp.asarray(cluster.nodes.valid),
            jnp.asarray(cluster.nodes.group),
            jnp.asarray(cluster.nodes.tainted),
            jnp.asarray(cluster.nodes.cordoned),
            jnp.asarray(cluster.nodes.creation_ns),
            aggs.node_pods_remaining)
    m0, k10, k20 = order_tail.order_keys_jit(*cols)
    m0n, k10n, k20n = (np.asarray(m0), np.asarray(k10), np.asarray(k20))
    perm0 = np.asarray(order_tail.order_sort_jit(m0, k10, k20))

    # flip a spread of taints: exactly those (valid) lanes' keys change —
    # enough of them that the small parametrized bucket overflows
    nodes2 = dataclasses.replace(
        cluster.nodes,
        tainted=cluster.nodes.tainted ^ (np.arange(N) % 16 == 1))
    cols2 = (cols[0], jnp.asarray(nodes2.valid), jnp.asarray(nodes2.group),
             jnp.asarray(nodes2.tainted), jnp.asarray(nodes2.cordoned),
             jnp.asarray(nodes2.creation_ns), aggs.node_pods_remaining)
    offs = np.zeros(G + 1, np.int32)
    offs[-1] = 3
    m1, k11, k21, perm, scale_down, count = order_tail.order_update_jit(
        *cols2, jnp.asarray(m0n.copy()), jnp.asarray(k10n.copy()),
        jnp.asarray(k20n.copy()), jnp.asarray(perm0.copy()),
        jnp.asarray(offs), bucket)
    want_m, want_k1, want_k2 = order_tail.order_keys_jit(*cols2)
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(want_m))
    np.testing.assert_array_equal(np.asarray(k11), np.asarray(want_k1))
    np.testing.assert_array_equal(np.asarray(k21), np.asarray(want_k2))
    want_dirty = ((np.asarray(want_m) != m0n)
                  | (np.asarray(want_k1) != k10n)
                  | (np.asarray(want_k2) != k20n))
    assert want_dirty.any(), "taint flips must move keys"
    assert int(count) == int(want_dirty.sum())
    if int(count) <= bucket:
        want_perm = np.asarray(order_tail.order_sort_jit(
            want_m, want_k1, want_k2))
        np.testing.assert_array_equal(np.asarray(perm), want_perm)
        np.testing.assert_array_equal(np.asarray(scale_down),
                                      np.roll(want_perm, -3))


def test_order_keys_reproduce_decide_permutation():
    """The order-state formulation (node_order_keys -> order_sort_jit) is
    bit-identical to the ordered decide's own permutation — the contract
    that lets an incremental ordered tick substitute its repaired
    permutation for the kernel's sort output."""
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    G, P, N = 8, 256, 128
    cluster = _random_cluster(rng, G, P, N)
    cluster.groups.emptiest[::2] = True
    dev = jax.device_put(cluster)
    out = jax.block_until_ready(kernel.decide_jit(dev, NOW))
    aggs = kernel.compute_aggregates_jit(dev)
    perm = order_tail.order_sort_jit(*order_tail.order_keys_jit(
        jnp.asarray(cluster.groups.emptiest), jnp.asarray(cluster.nodes.valid),
        jnp.asarray(cluster.nodes.group), jnp.asarray(cluster.nodes.tainted),
        jnp.asarray(cluster.nodes.cordoned),
        jnp.asarray(cluster.nodes.creation_ns), aggs.node_pods_remaining))
    np.testing.assert_array_equal(np.asarray(out.untaint_order),
                                  np.asarray(perm))
    np.testing.assert_array_equal(
        np.asarray(out.scale_down_order),
        np.roll(np.asarray(perm), -int(np.asarray(out.tainted_offsets)[-1])))
