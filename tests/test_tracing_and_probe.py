"""Tracing hooks (utils/tracing.py) and the accelerator probe (jaxconfig.py).

The reference has neither subsystem (its only tracing is a wall-time debug log,
/root/reference/pkg/controller/controller.go:448-449); both are TPU-build
additions, so their contracts are locked here rather than by a parity table:
the tracer must actually produce a TensorBoard-loadable trace and stop after
``max_ticks``, and the probe must degrade (not hang), write its audit line,
and cache its verdict.
"""

from __future__ import annotations

import os
import subprocess

import jax
import jax.numpy as jnp

from escalator_tpu.utils.tracing import TickTracer


def test_tick_tracer_writes_trace_and_stops(tmp_path):
    tracer = TickTracer(trace_dir=str(tmp_path), max_ticks=2)
    for _ in range(4):  # two ticks past the budget: must be plain no-ops
        with tracer.tick():
            jax.block_until_ready(jnp.ones(8) + 1)
    assert tracer._remaining == 0 and not tracer._active
    written = [
        os.path.join(dirpath, f)
        for dirpath, _, files in os.walk(tmp_path)
        for f in files
    ]
    assert written, "profiler trace produced no files"


def test_tick_tracer_disabled_without_dir():
    tracer = TickTracer(trace_dir=None, max_ticks=5)
    with tracer.tick():
        pass
    assert not tracer._active
    tracer.close()  # idempotent no-op


def test_tick_tracer_close_flushes_partial_trace(tmp_path):
    tracer = TickTracer(trace_dir=str(tmp_path), max_ticks=100)
    with tracer.tick():
        jax.block_until_ready(jnp.ones(8) * 2)
    assert tracer._active  # budget not exhausted: trace still open
    tracer.close()  # the CLI shutdown path
    assert not tracer._active and tracer._remaining == 0


def _fresh_probe(monkeypatch):
    from escalator_tpu import jaxconfig

    monkeypatch.setattr(jaxconfig, "_probe_result", None)
    # defeat the library-embedding fast paths (this test process HAS live cpu
    # backends and a cpu pin) so the probe-campaign logic actually runs
    monkeypatch.setattr(jaxconfig, "_backends_already_initialized",
                        lambda: False)
    monkeypatch.setattr(jaxconfig, "_pinned_to_cpu", lambda: False)
    return jaxconfig


def test_probe_timeout_degrades_and_logs(tmp_path, monkeypatch):
    jaxconfig = _fresh_probe(monkeypatch)

    def hang(*a, **k):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=k.get("timeout", 0))

    monkeypatch.setattr(subprocess, "run", hang)
    logf = tmp_path / "attempts.log"
    # un-pin the platform first (conftest pins cpu for every test), so the
    # assertion below actually exercises the probe's degrade path rather than
    # passing vacuously; restored right after.
    jax.config.update("jax_platforms", None)
    try:
        ok = jaxconfig.ensure_responsive_accelerator(
            timeout_sec=1.0, attempts=2, retry_wait_sec=0.0,
            attempt_log=str(logf),
        )
        # platform must be pinned to CPU so a wedged tunnel cannot hang callers
        assert jax.config.jax_platforms == "cpu"
    finally:
        jax.config.update("jax_platforms", "cpu")
    assert ok is False
    lines = logf.read_text().strip().splitlines()
    assert len(lines) == 2  # one audit line per attempt
    assert all("no answer" in line for line in lines)


def test_probe_success_short_circuits_retries(monkeypatch, tmp_path):
    jaxconfig = _fresh_probe(monkeypatch)
    calls = []

    def ok_run(*a, **k):
        calls.append(a)
        return subprocess.CompletedProcess(a, returncode=0)

    monkeypatch.setattr(subprocess, "run", ok_run)
    logf = tmp_path / "attempts.log"
    assert jaxconfig.ensure_responsive_accelerator(
        attempts=3, retry_wait_sec=0.0, attempt_log=str(logf)
    ) is True
    assert len(calls) == 1  # no pointless retries after a healthy answer
    assert "OK" in logf.read_text()


def test_probe_result_is_cached(monkeypatch):
    jaxconfig = _fresh_probe(monkeypatch)
    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: subprocess.CompletedProcess(a, returncode=0),
    )
    assert jaxconfig.ensure_responsive_accelerator() is True

    def boom(*a, **k):  # a second probe campaign must never start
        raise AssertionError("probe re-ran despite cached result")

    monkeypatch.setattr(subprocess, "run", boom)
    assert jaxconfig.ensure_responsive_accelerator() is True


def test_probe_unwritable_log_is_not_fatal(monkeypatch):
    jaxconfig = _fresh_probe(monkeypatch)
    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: subprocess.CompletedProcess(a, returncode=0),
    )
    assert jaxconfig.ensure_responsive_accelerator(
        attempt_log="/nonexistent-dir/attempts.log"
    ) is True


def test_profiler_server_failure_is_nonfatal(monkeypatch):
    from escalator_tpu.utils import tracing

    called = {}

    def fail(port):
        called["port"] = port
        raise RuntimeError("already started")

    monkeypatch.setattr(jax.profiler, "start_server", fail)
    tracing.start_profiler_server(9999)  # must not raise
    assert called["port"] == 9999


def test_probe_fast_paths_skip_subprocess(monkeypatch):
    """When this process already holds live jax backends (pinning is a no-op
    and a parent's exclusive device lock would fail the subprocess falsely),
    or is pinned to cpu (nothing can wedge), the probe must report healthy
    WITHOUT spawning anything — the library-embedding contract that lets
    make_backend/make_server probe unconditionally."""
    from escalator_tpu import jaxconfig

    monkeypatch.setattr(jaxconfig, "_probe_result", None)

    def boom(*a, **k):
        raise AssertionError("fast path must not spawn a probe subprocess")

    monkeypatch.setattr(subprocess, "run", boom)
    # this test process genuinely has initialized cpu backends AND the pin,
    # so the real helpers (not stubs) drive the fast path
    assert jaxconfig._backends_already_initialized() or jaxconfig._pinned_to_cpu()
    assert jaxconfig.ensure_responsive_accelerator() is True
    # and the verdict is not cached: a later unpinned process still probes
    assert jaxconfig._probe_result is None
