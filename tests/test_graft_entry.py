"""The driver-facing surface (``__graft_entry__``) must stay safe and correct.

``entry()`` hands (fn, example_args) to a DRIVER that jit-compiles fn itself;
on this machine a sitecustomize pins the default jax platform to the TPU
tunnel, which can wedge indefinitely at backend init, so entry() must
probe-and-pin (the guard ``dryrun_multichip`` always had) before the caller's
compile can touch a backend. Reproduced live 2026-07-31: an unguarded
``jit(entry_fn).compile()`` against the wedged tunnel slept forever in the
axon client's retry loop.
"""

import jax
import numpy as np

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    # flagship decide: per-group scale deltas for the 4-group example cluster
    assert int(out.nodes_delta.shape[0]) == 4
    jax.block_until_ready(out.nodes_delta)


def test_entry_probes_before_returning(monkeypatch):
    calls = []
    from escalator_tpu import jaxconfig

    monkeypatch.setattr(
        jaxconfig,
        "ensure_responsive_accelerator",
        lambda **kw: calls.append(kw) or True,
    )
    fn, args = graft.entry()
    assert calls, "entry() must probe-and-pin before the driver compiles fn"


def test_dryrun_multichip_smoke():
    # tests/conftest pins cpu with 8 virtual devices; the full sharded
    # programs (1-D, hybrid, pod-axis, grid) must compile and bit-match
    graft.dryrun_multichip(8)
