"""Fault-injection chaos suite: forced failures through the REAL stack.

Each scenario arms the injection layer (escalator_tpu.chaos) at a site
compiled into production code, runs the genuine controller/backend path,
and asserts the three-part acceptance bar from ROADMAP item 5 / ISSUE 6:

1. graceful degradation — the documented fallback is taken (retry ladder →
   local backend, dead audit worker → synchronous audit, wedged tick →
   watchdog crash-to-restart, lost lease → deposition);
2. state reconciled — decisions stay semantically identical to the
   non-faulted run (or converge back after the repair the fault forces);
3. every injected fault visible — in the chaos metric AND in flight
   records/dumps.
"""

import glob
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from escalator_tpu.chaos import CHAOS, ChaosInjected, ChaosMonkey, install_from_env
from escalator_tpu.metrics import metrics

NOW = 1_700_000_000


@pytest.fixture(autouse=True)
def _disarm():
    CHAOS.disarm()
    yield
    CHAOS.disarm()


def counter_value(counter, *labels):
    c = counter.labels(*labels) if labels else counter
    return c._value.get()


class TestChaosMonkey:
    def test_disarmed_is_inert(self):
        m = ChaosMonkey()
        assert not m.should_fire("anything")
        m.inject("anything")   # no raise

    def test_times_and_every_and_after(self):
        m = ChaosMonkey()
        m.arm("s", times=2, every=2, after=1)
        # call 1 skipped (after); of the eligible calls 2,3,4,... every
        # SECOND one fires (calls 3 and 5); then times=2 exhausts the rule
        fired = [m.should_fire("s") for _ in range(8)]
        assert fired == [False, False, True, False, True, False, False,
                         False]
        assert m.fired("s") == 2

    def test_inject_raises_typed_error(self):
        m = ChaosMonkey()
        m.arm("s")
        with pytest.raises(ChaosInjected, match="'s'"):
            m.inject("s")

    def test_env_spec_parsing(self):
        m_rules = install_from_env(
            "tick_wedge:times=1,delay=0 ; plugin_rpc:every=3,code=unavailable")
        try:
            assert m_rules == 2
            assert CHAOS.params("plugin_rpc")["code"] == "unavailable"
        finally:
            CHAOS.disarm()

    def test_env_spec_malformed_fails_fast(self):
        with pytest.raises(ValueError, match="k=v"):
            install_from_env("plugin_rpc:nonsense")

    def test_firing_increments_metric(self):
        before = counter_value(metrics.chaos_injections, "unit-test-site")
        CHAOS.arm("unit-test-site", times=1)
        assert CHAOS.should_fire("unit-test-site")
        assert counter_value(
            metrics.chaos_injections, "unit-test-site") == before + 1


@pytest.fixture(scope="module")
def plugin():
    from escalator_tpu.plugin.client import ComputeClient
    from escalator_tpu.plugin.server import make_server

    server = make_server("127.0.0.1:0")
    port = server._escalator_bound_port
    server.start()
    client = ComputeClient(f"127.0.0.1:{port}")
    yield client
    client.close()
    server.stop(grace=None)


def _group_inputs():
    from escalator_tpu.core import semantics as sem
    from escalator_tpu.testsupport.builders import (
        NodeOpts,
        PodOpts,
        build_test_nodes,
        build_test_pods,
    )

    pods = build_test_pods(4, PodOpts(cpu=[500], mem=[10**8]))
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    cfg = sem.GroupConfig(
        min_nodes=0, max_nodes=100, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=70,
        slow_removal_rate=1, fast_removal_rate=2,
    )
    return [(pods, nodes, cfg, sem.GroupState())]


class TestPluginRpcChaos:
    """Injected RPC failures through the real client/server pair: retries
    absorb transients, fallbacks are counted by code, the breaker pins an
    outage, a probe recovers — decisions identical throughout."""

    def _backend(self, plugin, **kw):
        from escalator_tpu.plugin.client import GrpcBackend, RetryPolicy

        kw.setdefault("retry", RetryPolicy(base_backoff_sec=0.005,
                                           max_backoff_sec=0.02))
        return GrpcBackend(plugin.address, **kw)

    def test_transient_failure_retried_no_fallback(self, plugin):
        backend = self._backend(plugin)
        gi = _group_inputs()
        want = backend.decide(gi, NOW)[0].decision
        retries0 = counter_value(metrics.plugin_rpc_retries)
        CHAOS.arm("plugin_rpc", times=1)
        got = backend.decide(gi, NOW)[0].decision
        assert got == want                       # zero semantic divergence
        assert counter_value(metrics.plugin_rpc_retries) == retries0 + 1
        assert not backend.breaker_open
        # the injected fault is visible in the tick's flight record
        from escalator_tpu.observability import RECORDER

        rec = RECORDER.last()
        assert rec["backend"] == "grpc" and rec.get("chaos") == "plugin_rpc"
        assert "fallback" not in rec             # retry succeeded in-band

    def test_outage_opens_breaker_then_probe_recovers(self, plugin):
        from escalator_tpu.observability import RECORDER

        backend = self._backend(plugin, breaker_threshold=2,
                                breaker_probe_after=3)
        gi = _group_inputs()
        want = backend.decide(gi, NOW)[0].decision
        fb0 = counter_value(metrics.plugin_fallback, "UNAVAILABLE")
        co0 = counter_value(metrics.plugin_fallback, "circuit-open")

        CHAOS.arm("plugin_rpc")                  # hard outage
        for _ in range(2):
            assert backend.decide(gi, NOW)[0].decision == want
        assert backend.breaker_open
        assert counter_value(
            metrics.plugin_fallback, "UNAVAILABLE") == fb0 + 2
        # open circuit: served from the fallback WITHOUT touching the RPC
        fired = CHAOS.fired("plugin_rpc")
        assert backend.decide(gi, NOW)[0].decision == want
        assert CHAOS.fired("plugin_rpc") == fired
        assert counter_value(
            metrics.plugin_fallback, "circuit-open") == co0 + 1
        rec = RECORDER.last()
        assert rec.get("fallback_code") == "circuit-open"

        # plugin recovers: the next probe tick closes the circuit
        CHAOS.disarm("plugin_rpc")
        for _ in range(4):
            assert backend.decide(gi, NOW)[0].decision == want
        assert not backend.breaker_open

    def test_failed_probe_keeps_circuit_open(self, plugin):
        backend = self._backend(plugin, breaker_threshold=1,
                                breaker_probe_after=2)
        gi = _group_inputs()
        want = backend.decide(gi, NOW)[0].decision
        CHAOS.arm("plugin_rpc")
        for _ in range(5):   # failure + open-serving + failing probes
            assert backend.decide(gi, NOW)[0].decision == want
        assert backend.breaker_open


def _taintless_decider(refresh_every, **kw):
    """An incremental decider over a no-taint, no-emptiest cluster: the
    audit-chaos corruption (node_pods_remaining lane 0) is then provably
    decision-neutral — npr feeds only reap (needs taints), emptiest
    ordering (disabled), and its own output column."""
    from escalator_tpu.analysis.registry import representative_cluster
    from escalator_tpu.core.arrays import NO_TAINT_TIME
    from escalator_tpu.ops.device_state import (
        DeviceClusterCache,
        IncrementalDecider,
    )

    host = representative_cluster(seed=41)
    host.nodes.tainted[:] = False
    host.nodes.cordoned[:] = False
    host.nodes.taint_time_sec[:] = NO_TAINT_TIME
    host.groups.emptiest[:] = False
    cache = DeviceClusterCache(host)
    inc = IncrementalDecider(cache, refresh_every=refresh_every, **kw)
    return host, cache, inc


def _churn_tick(host, cache, inc, rng, t):
    idx = np.unique(rng.integers(0, host.pods.valid.shape[0], 4))
    host.pods.cpu_milli[idx] = rng.integers(100, 8000, len(idx))
    inc.apply_gathered(cache.gather_deltas(idx.astype(np.int64),
                                           np.empty(0, np.int64)))
    return inc.decide(NOW + t, False)


class TestAuditMismatchChaos:
    def test_corruption_detected_repaired_and_decision_neutral(
            self, tmp_path, monkeypatch):
        import jax

        from escalator_tpu.ops.kernel import decide_jit, lazy_orders_decide

        monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
        host, cache, inc = _taintless_decider(
            refresh_every=3, on_mismatch="repair", background=True)
        rng = np.random.default_rng(41)
        mm0 = counter_value(metrics.incremental_audit_mismatch)
        CHAOS.arm("audit_mismatch", times=1)
        saw_repair = False
        for t in range(8):
            out, ordered = _churn_tick(host, cache, inc, rng, t)
            ref, ref_ordered = lazy_orders_decide(
                lambda w, _now=NOW + t: jax.block_until_ready(
                    decide_jit(cache.cluster, _now, with_orders=w)),
                False)
            assert ordered == ref_ordered
            # zero semantic divergence THROUGHOUT the fault: status, delta
            # and orders never move (the corrupted lane is decision-neutral
            # by construction — see _taintless_decider)
            for f in ("status", "nodes_delta", "scale_down_order",
                      "untaint_order", "reap_mask"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(out, f)),
                    np.asarray(getattr(ref, f)), err_msg=f"tick {t}: {f}")
            if counter_value(metrics.incremental_audit_mismatch) > mm0:
                saw_repair = True
        inc.drain_audit()
        assert CHAOS.fired("audit_mismatch") == 1
        assert saw_repair or counter_value(
            metrics.incremental_audit_mismatch) > mm0
        # repair reconciled: the maintained npr column is exact again
        fresh = _full_npr(cache)
        np.testing.assert_array_equal(
            np.asarray(inc.aggregates.node_pods_remaining), fresh)
        # and the mismatch dumped a flight record
        assert glob.glob(os.path.join(str(tmp_path),
                                      "*audit-mismatch*.json"))


def _full_npr(cache):
    from escalator_tpu.ops.kernel import compute_aggregates_jit

    return np.asarray(
        compute_aggregates_jit(cache.cluster).node_pods_remaining)


class TestAuditWorkerDeathChaos:
    def test_dead_worker_degrades_to_sync_audit(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
        host, cache, inc = _taintless_decider(
            refresh_every=2, on_mismatch="raise", background=True)
        rng = np.random.default_rng(43)
        wd0 = counter_value(metrics.audit_worker_failures)
        CHAOS.arm("audit_worker", times=1)
        for t in range(6):
            _churn_tick(host, cache, inc, rng, t)
        inc.drain_audit()
        assert CHAOS.fired("audit_worker") == 1
        assert counter_value(metrics.audit_worker_failures) == wd0 + 1
        # the sync fallback audit ran and passed: state was never corrupted
        assert inc.last_audit_ok
        assert glob.glob(os.path.join(str(tmp_path),
                                      "*audit-worker-death*.json"))
        # the decider keeps working (and later audits stay background-clean)
        for t in range(6, 10):
            _churn_tick(host, cache, inc, rng, t)
        assert inc.drain_audit()

    def test_dead_worker_never_deadlocks_snapshot_gate(self):
        """The snap_ready gate is released in a finally: even a worker that
        dies mid-audit must never wedge the next tick's donation gate."""
        host, cache, inc = _taintless_decider(
            refresh_every=1, on_mismatch="raise", background=True)
        rng = np.random.default_rng(47)
        CHAOS.arm("audit_worker")   # EVERY audit worker dies
        done = threading.Event()

        def run():
            for t in range(4):
                _churn_tick(host, cache, inc, rng, t)
            done.set()

        worker = threading.Thread(target=run, daemon=True)
        worker.start()
        assert done.wait(60), "tick thread wedged behind a dead audit worker"


class TestWedgeChaos:
    def test_wedged_tick_trips_watchdog_with_dump(self, tmp_path):
        """ESCALATOR_TPU_CHAOS=tick_wedge through the real CLI: the first
        tick sleeps past the watchdog limit, the process crash-to-restarts
        (exit 70) and dumps the flight ring first."""
        env = dict(os.environ)
        env["ESCALATOR_TPU_CHAOS"] = "tick_wedge:times=1,delay=60"
        env["ESCALATOR_TPU_WATCHDOG_LIMIT_SEC"] = "3"
        env["ESCALATOR_TPU_DUMP_DIR"] = str(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-m", "escalator_tpu",
             "--nodegroups", "examples/nodegroups.yaml",
             "--sim-state", "examples/cluster-state.yaml",
             "--backend", "golden", "--scaninterval", "60s",
             "--address", "127.0.0.1:0"],
            env=env, capture_output=True, text=True, timeout=60,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 70, (proc.returncode, proc.stderr[-500:])
        assert "no tick completed" in proc.stderr
        assert glob.glob(os.path.join(str(tmp_path), "*flight-wedge*.json"))


class TestLeaseLossChaos:
    def test_renew_failures_depose_after_deadline(self):
        """Lease loss mid-run: chaos makes every renewal fail; the elector
        must hold through the deadline (transient-tolerance contract), then
        depose exactly as a genuine lease loss would."""
        from escalator_tpu.k8s.election import (
            InMemoryResourceLock,
            LeaderElectionConfig,
            LeaderElector,
        )
        from escalator_tpu.utils.clock import MockClock
        from tests.test_election_and_cli import FakeStopOnce

        cfg = LeaderElectionConfig(
            lease_duration_sec=5.0, renew_deadline_sec=3.0,
            retry_period_sec=0.5)
        clock = MockClock()
        deposed = threading.Event()
        e = LeaderElector(InMemoryResourceLock(), cfg, identity="a",
                          clock=clock, on_deposed=deposed.set)
        assert e.run(blocking_acquire_timeout=1)
        CHAOS.arm("lease_renew")
        # 2 failed rounds (1.0s) < deadline: still leader
        e._stop = FakeStopOnce(clock, cfg.retry_period_sec, rounds=2)
        e._renew_loop()
        assert not deposed.is_set() and e.is_leader
        # 8 more failed rounds (4.0s) > deadline: deposed
        e._stop = FakeStopOnce(clock, cfg.retry_period_sec, rounds=8)
        e._renew_loop()
        assert deposed.is_set() and not e.is_leader
        assert CHAOS.fired("lease_renew") >= 3
