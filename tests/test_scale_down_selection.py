"""scale_down_selection: emptiest_first victim ordering across every layer.

The reference ships only oldest-first and documents alternative selection
methods as future work (docs/node-termination.md); emptiest_first ranks
victims by non-daemonset pod count (ties oldest-first), minimizing evictions.
Golden model, batched kernel, and controller must agree; oldest_first groups
must stay bit-identical to the reference order even in mixed-mode batches.
"""

import numpy as np
import pytest

from escalator_tpu.core import semantics as sem
from escalator_tpu.core.arrays import pack_cluster
from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.ops import kernel
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_node,
    build_test_pod,
)

NOW = np.int64(1_700_000_000)


def _cfg(selection="oldest_first"):
    return sem.GroupConfig(
        min_nodes=0, max_nodes=100, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=70, slow_removal_rate=1,
        fast_removal_rate=2, soft_delete_grace_sec=300,
        hard_delete_grace_sec=900, scale_down_selection=selection,
    )


def _group(selection, n_nodes=6, pods_on=()):
    """n_nodes nodes aged oldest-first by index; pods_on[i] pods on node i."""
    nodes = [
        build_test_node(NodeOpts(name=f"{selection}-n{i}", cpu=4000,
                                 mem=16 * 10**9, creation_time_ns=(i + 1) * 10**9))
        for i in range(n_nodes)
    ]
    pods = []
    for i, count in enumerate(pods_on):
        for j in range(count):
            pods.append(
                build_test_pod(PodOpts(name=f"{selection}-p{i}-{j}", cpu=[100],
                                       mem=[10**8], node_name=nodes[i].name))
            )
    return (pods, nodes, _cfg(selection), sem.GroupState())


class TestSemantics:
    def test_emptiest_first_ordering(self):
        pods, nodes, _, _ = _group("emptiest_first", 4, pods_on=(3, 0, 2, 0))
        from escalator_tpu.k8s import types as k8s

        info = k8s.create_node_name_to_info_map(pods, nodes)
        remaining = [
            sum(1 for p in info.get(n.name, (None, []))[1]
                if not k8s.pod_is_daemonset(p))
            for n in nodes
        ]
        order = sem.nodes_emptiest_first(nodes, remaining)
        # empty nodes first (oldest of the empties leads), then 2 pods, then 3
        assert order == [1, 3, 2, 0]

    def test_config_default_is_oldest(self):
        assert _cfg().scale_down_selection == "oldest_first"


class TestKernelParity:
    def test_mixed_modes_in_one_batch(self):
        """One batch holding both modes: each group gets ITS order; the
        oldest_first group's order is byte-identical to the pure-age sort."""
        g_old = _group("oldest_first", 4, pods_on=(2, 0, 1, 0))
        g_empty = _group("emptiest_first", 4, pods_on=(3, 0, 2, 0))
        cluster = pack_cluster([g_old, g_empty])
        out = kernel.decide_jit(cluster, NOW)
        down = np.asarray(out.scale_down_order)
        offs = np.asarray(out.untainted_offsets)

        # group 0 (oldest_first): ages ascending -> flat indices 0..3
        assert list(down[offs[0]:offs[1]]) == [0, 1, 2, 3]
        # group 1 (emptiest_first): flat indices 4..7, pods (3,0,2,0)
        assert list(down[offs[1]:offs[2]]) == [5, 7, 6, 4]

    def test_kernel_matches_golden_backend(self):
        from escalator_tpu.controller.backend import GoldenBackend, JaxBackend

        groups = [
            _group("emptiest_first", 5, pods_on=(1, 4, 0, 2, 0)),
            _group("oldest_first", 5, pods_on=(1, 4, 0, 2, 0)),
        ]

        def fresh():
            return [
                (p, n, c, sem.GroupState(**s.__dict__)) for p, n, c, s in groups
            ]

        golden = GoldenBackend().decide(fresh(), int(NOW))
        jaxed = JaxBackend().decide(fresh(), int(NOW))
        for g, j in zip(golden, jaxed, strict=True):
            assert [n.name for n in g.scale_down_order] == [
                n.name for n in j.scale_down_order
            ]


class TestConfig:
    def test_yaml_and_validation(self):
        opts = ngmod.unmarshal_node_group_options(
            """
node_groups:
  - name: "empty-first"
    label_key: customer
    label_value: shared
    cloud_provider_group_name: asg1
    min_nodes: 1
    max_nodes: 10
    taint_upper_capacity_threshold_percent: 45
    taint_lower_capacity_threshold_percent: 30
    scale_up_threshold_percent: 70
    slow_node_removal_rate: 1
    fast_node_removal_rate: 2
    soft_delete_grace_period: 5m
    hard_delete_grace_period: 15m
    scale_up_cool_down_period: 10m
    scale_down_selection: emptiest_first
"""
        )
        assert opts[0].scale_down_selection == "emptiest_first"
        assert ngmod.validate_node_group(opts[0]) == []
        assert opts[0].to_group_config().scale_down_selection == "emptiest_first"

    def test_invalid_selection_rejected(self):
        opts = ngmod.NodeGroupOptions(
            name="x", label_key="k", label_value="v",
            cloud_provider_group_name="asg", min_nodes=1, max_nodes=5,
            taint_upper_capacity_threshold_percent=45,
            taint_lower_capacity_threshold_percent=30,
            scale_up_threshold_percent=70, slow_node_removal_rate=1,
            fast_node_removal_rate=2, soft_delete_grace_period="5m",
            hard_delete_grace_period="15m", scale_up_cool_down_period="10m",
            scale_down_selection="newest_first",
        )
        problems = ngmod.validate_node_group(opts)
        assert any("scale_down_selection" in p for p in problems), problems


class TestTieBreaking:
    """Exact-tie creation timestamps (and tied pod counts for emptiest_first)
    must order by input index — the deterministic tie-break CHANGELOG
    documents. Locks the combined multi-key lax.sort's iota key in
    ops.kernel (decide's _combined_order): a regression to an unstable or
    differently-keyed sort flips these orders silently."""

    def _orders(self, group):
        cluster = pack_cluster([group])
        out = kernel.decide_jit(cluster, NOW)
        u_off = np.asarray(out.untainted_offsets)
        t_off = np.asarray(out.tainted_offsets)
        down = list(np.asarray(out.scale_down_order)[u_off[0]:u_off[1]])
        up = list(np.asarray(out.untaint_order)[t_off[0]:t_off[1]])
        return down, up

    def test_all_creation_times_equal(self):
        nodes = [
            build_test_node(NodeOpts(name=f"tie-n{i}", cpu=4000,
                                     mem=16 * 10**9, creation_time_ns=10**9))
            for i in range(5)
        ]
        group = ([], nodes, _cfg("oldest_first"), sem.GroupState())
        down, _ = self._orders(group)
        assert down == [0, 1, 2, 3, 4]  # input order, exactly
        assert sem.nodes_oldest_first(nodes) == down

    def test_tied_pairs_keep_input_order_among_equals(self):
        ts = [3, 1, 3, 1, 2]  # pairs tie; golden sorts (ts, index)
        nodes = [
            build_test_node(NodeOpts(name=f"pair-n{i}", cpu=4000,
                                     mem=16 * 10**9,
                                     creation_time_ns=t * 10**9))
            for i, t in enumerate(ts)
        ]
        group = ([], nodes, _cfg("oldest_first"), sem.GroupState())
        down, _ = self._orders(group)
        assert down == sem.nodes_oldest_first(nodes) == [1, 3, 4, 0, 2]

    def test_untaint_ties_also_input_order(self):
        # young pair LAST in input: expected [2,3,0,1] differs from input
        # order, so a dropped/major-only sort cannot sneak past this
        ts = [1, 1, 2, 2]
        nodes = [
            build_test_node(NodeOpts(name=f"unt-n{i}", cpu=4000,
                                     mem=16 * 10**9, tainted=True,
                                     taint_time_sec=int(NOW) - 10,
                                     creation_time_ns=t * 10**9))
            for i, t in enumerate(ts)
        ]
        group = ([], nodes, _cfg("oldest_first"), sem.GroupState())
        _, up = self._orders(group)
        # newest first; among equal timestamps, input order
        assert up == sem.nodes_newest_first(nodes) == [2, 3, 0, 1]

    def test_emptiest_first_tied_counts_fall_back_to_age_then_index(self):
        # same pod count everywhere; two nodes also tie on age
        nodes = [
            build_test_node(NodeOpts(name=f"emp-n{i}", cpu=4000,
                                     mem=16 * 10**9,
                                     creation_time_ns=t * 10**9))
            for i, t in enumerate([2, 1, 2])
        ]
        pods = [
            build_test_pod(PodOpts(name=f"emp-p{i}", cpu=[100], mem=[10**8],
                                   node_name=n.name))
            for i, n in enumerate(nodes)
        ]
        group = (pods, nodes, _cfg("emptiest_first"), sem.GroupState())
        down, _ = self._orders(group)
        assert down == sem.nodes_emptiest_first(nodes, [1, 1, 1]) == [1, 0, 2]


class TestEmptySelectionWindows:
    """The empty-selection fast path (ops.kernel skips a sort via lax.cond
    when nothing is selected) is safe only because consumers read orderings
    exclusively through their per-group offset windows — which are empty
    exactly when the selection is. Lock that invariant: if it breaks, the
    skipped sort's placeholder content becomes observable."""

    def test_no_tainted_nodes_means_empty_untaint_windows(self):
        nodes = [
            build_test_node(NodeOpts(name=f"h-n{i}", cpu=4000, mem=16 * 10**9,
                                     creation_time_ns=(i + 1) * 10**9))
            for i in range(6)
        ]
        out = kernel.decide_jit(
            pack_cluster([([], nodes, _cfg(), sem.GroupState())]), NOW)
        t_off = np.asarray(out.tainted_offsets)
        assert (t_off == 0).all()  # every untaint window empty
        # and the scale-down windows still carry the real sorted order
        u_off = np.asarray(out.untainted_offsets)
        down = list(np.asarray(out.scale_down_order)[u_off[0]:u_off[1]])
        assert down == sem.nodes_oldest_first(nodes)

    def test_all_tainted_means_empty_scaledown_windows(self):
        nodes = [
            build_test_node(NodeOpts(name=f"d-n{i}", cpu=4000, mem=16 * 10**9,
                                     tainted=True, taint_time_sec=int(NOW) - 5,
                                     creation_time_ns=(i + 1) * 10**9))
            for i in range(6)
        ]
        out = kernel.decide_jit(
            pack_cluster([([], nodes, _cfg(), sem.GroupState())]), NOW)
        u_off = np.asarray(out.untainted_offsets)
        assert (u_off == 0).all()  # every scale-down window empty
        t_off = np.asarray(out.tainted_offsets)
        up = list(np.asarray(out.untaint_order)[t_off[0]:t_off[1]])
        assert up == sem.nodes_newest_first(nodes)


@pytest.mark.parametrize("with_orders,want_sorts", [(True, 1), (False, 0)],
                         ids=["ordered", "light"])
def test_decide_sort_count_by_variant(with_orders, want_sorts):
    """Structural lock, platform-independent (the TPU-trace twin lives in
    test_trace_artifact.py): the ordered decide must contain exactly ONE
    sort instruction — the combined 4-key ordering sort (a second means the
    orderings split back into per-selection sorts, 2x the dominant tail
    cost) — and the with_orders=False light program (the lazy-orders fast
    path, kernel.lazy_orders_decide) must contain ZERO, or the steady-state
    win is silently forfeited. Counted on the pre-optimization StableHLO:
    backend passes may legitimately split a sort, so the compiled module's
    count is NOT platform-stable — the traced program's is."""
    import re

    import jax

    from tests.test_podaxis import _random_cluster

    cluster = _random_cluster(np.random.default_rng(0), G=8, P=256, N=64)
    txt = jax.jit(
        lambda c, t: kernel.decide(c, t, with_orders=with_orders)
    ).lower(cluster, NOW).as_text()
    insts = re.findall(r"stablehlo\.sort", txt)
    assert len(insts) == want_sorts, (
        f"with_orders={with_orders}: expected {want_sorts} stablehlo.sort, "
        f"got {len(insts)}")
