"""Simulation harness: multi-tick closed-loop behavior with the synthetic cloud."""

import json


from escalator_tpu import sim
from escalator_tpu.controller.backend import GoldenBackend
from escalator_tpu.k8s.cache import EventfulClient
from escalator_tpu.testsupport.builders import NodeOpts, build_test_nodes

from tests.test_controller import LABEL_KEY, LABEL_VALUE, make_opts


def make_client(num_nodes=4):
    nodes = build_test_nodes(num_nodes, NodeOpts(
        cpu=1000, mem=4 * 10**9, label_key=LABEL_KEY, label_value=LABEL_VALUE))
    return EventfulClient(nodes=nodes)


def test_scale_up_then_converge():
    """Demand spike -> scale up -> synthetic cloud delivers -> deltas go to zero."""
    client = make_client(4)
    # cooldown must cover delivery latency (2 ticks = 120s) or the controller
    # double-buys — the exact hysteresis the scale lock exists for
    ng = make_opts(scale_up_cool_down_period="5m")
    workload = [{
        "at_tick": 0,
        "add_pods": {"count": 30, "cpu_milli": 500, "mem_bytes": 10**8,
                     "node_selector": {LABEL_KEY: LABEL_VALUE}},
    }]
    timeline = sim.run_simulation(
        [ng], client, ticks=12, tick_interval_sec=60, node_ready_ticks=2,
        workload_events=workload, backend=GoldenBackend(),
    )
    assert timeline[0]["deltas"]["buildeng"] > 0       # spike triggers scale-up
    assert timeline[-1]["deltas"]["buildeng"] == 0     # converged
    assert timeline[-1]["nodes"] > 4                   # cloud delivered capacity
    # post-convergence utilisation at/below the slack target
    final_nodes = timeline[-1]["nodes"]
    assert 30 * 500 / (final_nodes * 1000) * 100 <= ng.scale_up_threshold_percent


def test_scale_down_and_reap_cycle():
    """Workload drains -> taint, grace passes, reaper deletes down to min."""
    client = make_client(8)
    ng = make_opts(min_nodes=2, fast_node_removal_rate=3,
                   soft_delete_grace_period="2m", hard_delete_grace_period="20m")
    timeline = sim.run_simulation(
        [ng], client, ticks=15, tick_interval_sec=60, node_ready_ticks=2,
        workload_events=[], backend=GoldenBackend(),
    )
    # idle cluster: nodes tainted then reaped down toward the minimum
    assert timeline[0]["deltas"]["buildeng"] < 0
    assert timeline[-1]["nodes"] == 2
    assert timeline[-1]["tainted"] == 0


def test_cli_main_emits_json(tmp_path, capsys):
    from tests.test_election_and_cli import NODEGROUPS_YAML, SIM_STATE_YAML

    ngf = tmp_path / "ng.yaml"
    ngf.write_text(NODEGROUPS_YAML)
    stf = tmp_path / "state.yaml"
    stf.write_text(SIM_STATE_YAML)
    rc = sim.main([
        "--nodegroups", str(ngf), "--sim-state", str(stf),
        "--ticks", "3", "--backend", "golden",
    ])
    assert rc == 0
    lines = [json.loads(x) for x in capsys.readouterr().out.strip().splitlines()]
    assert len(lines) == 3
    assert all("deltas" in r and "provider_targets" in r for r in lines)


def test_short_cooldown_overscales_then_recovers():
    """Negative-space check: a cooldown shorter than delivery latency causes a
    double-buy, which the slow-removal path then corrects — the documented
    reason scale_up_cool_down_period must cover boot+registration time."""
    client = make_client(4)
    ng = make_opts(scale_up_cool_down_period="30s", min_nodes=1)
    workload = [{
        "at_tick": 0,
        "add_pods": {"count": 30, "cpu_milli": 500, "mem_bytes": 10**8,
                     "node_selector": {LABEL_KEY: LABEL_VALUE}},
    }]
    timeline = sim.run_simulation(
        [ng], client, ticks=10, tick_interval_sec=60, node_ready_ticks=2,
        workload_events=workload, backend=GoldenBackend(),
    )
    peak = max(r["nodes"] for r in timeline)
    assert peak > 22  # double-bought past the single-shot answer (4 + 18)
    assert any(r["deltas"]["buildeng"] < 0 for r in timeline)  # corrects back


def test_sweep_summary_on_final_tick():
    """--sweep-deltas: the final record carries each group's minimal feasible
    scale-up delta (or the num_candidates sentinel when out of range)."""
    from escalator_tpu.controller.backend import JaxBackend

    client = make_client(4)
    ng = make_opts(scale_up_cool_down_period="30m")  # stay locked: demand unmet
    workload = [{
        "at_tick": 0,
        "add_pods": {"count": 30, "cpu_milli": 500, "mem_bytes": 10**8,
                     "node_selector": {LABEL_KEY: LABEL_VALUE}},
    }]
    timeline = sim.run_simulation(
        [ng], client, ticks=3, tick_interval_sec=60, node_ready_ticks=10,
        workload_events=workload, backend=JaxBackend(), sweep_candidates=64,
    )
    sweep = timeline[-1]["sweep_min_feasible_delta"]
    # 30 pods x 500m on 4x1000m nodes = 375%; needs more nodes; candidate range
    # 64 is enough, so a real (non-sentinel) delta comes back
    assert 0 < sweep["buildeng"] < 64
    assert "sweep_min_feasible_delta" not in timeline[0]
