"""Fleet-scale decision service (round 14): kernel parity, the engine's
multi-tenant soak (churn: add/evict/grow mid-run), scheduler admission/
coalescing/fairness semantics, codec tenant framing (mixed-version
byte-identity, malformed tenants), and the gRPC fleet mode end-to-end."""

from __future__ import annotations

import random
import threading
import time
import types

import numpy as np
import pytest

from escalator_tpu.analysis import lockwitness
from escalator_tpu.analysis.registry import representative_cluster
from escalator_tpu.fleet import service as service_mod
from escalator_tpu.fleet import (
    AdmissionError,
    DecideRequest,
    EvictAck,
    EvictRequest,
    FleetEngine,
    FleetScheduler,
    TenantError,
    validate_tenant_id,
)
from escalator_tpu.ops import kernel

NOW = np.int64(1_700_000_000)

# tiny arena buckets: every jit in this module compiles at toy shapes
G, P, N = 6, 24, 12


def tiny_cluster(seed: int) -> "object":
    return representative_cluster(G, P, N, seed=seed)


def mutate(cluster, rng: np.random.Generator):
    """Random in-place churn across every lane class (the arrays are fresh
    per call in these tests, so in-place is safe)."""
    k = int(rng.integers(1, 4))
    for _ in range(k):
        what = rng.integers(0, 6)
        if what == 0:
            cluster.pods.cpu_milli[rng.integers(0, P)] += 50
        elif what == 1:
            i = rng.integers(0, P)
            cluster.pods.valid[i] = not cluster.pods.valid[i]
        elif what == 2:
            i = rng.integers(0, N)
            cluster.nodes.tainted[i] = not cluster.nodes.tainted[i]
        elif what == 3:
            cluster.nodes.group[rng.integers(0, N)] = rng.integers(0, G)
        elif what == 4:
            cluster.groups.locked[rng.integers(0, G)] ^= True
        else:
            cluster.pods.node[rng.integers(0, P)] = rng.integers(-1, N)
    return cluster


def assert_column_parity(fleet_arrays, cluster, now, msg=""):
    """The acceptance contract: the 13 decision columns bit-identical to
    the tenant's standalone decide on the same cluster."""
    import jax

    ref = kernel.decide_jit(jax.device_put(cluster), np.int64(now))
    for f in kernel.GROUP_DECISION_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(fleet_arrays, f)),
            np.asarray(getattr(ref, f)), err_msg=f"{msg}:{f}")
    return ref


# ---------------------------------------------------------------------------
# kernel layer
# ---------------------------------------------------------------------------


def test_fleet_decide_jit_matches_per_tenant_decide():
    from jax import tree_util

    import jax

    clusters = [tiny_cluster(s) for s in range(4)]
    stacked = tree_util.tree_map(lambda *xs: np.stack(xs), *clusters)
    nows = NOW + np.arange(4, dtype=np.int64) * 60
    out = kernel.fleet_decide_jit(jax.device_put(stacked), nows)
    for i, c in enumerate(clusters):
        ref = kernel.decide_jit(jax.device_put(c), nows[i],
                                with_orders=False)
        for f in kernel.GROUP_DECISION_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(out, f))[i], np.asarray(getattr(ref, f)),
                err_msg=f"tenant {i}: {f}")
        # the [N] tail is per-tenant too (reap eligibility at each now)
        np.testing.assert_array_equal(
            np.asarray(out.reap_mask)[i], np.asarray(ref.reap_mask))


def test_fleet_dirty_indices_shared_bucket():
    idx = kernel.fleet_dirty_indices(
        [np.array([1, 0, 1, 0, 0, 0], bool), np.zeros(6, bool)], 6)
    assert idx.shape == (2, 6)  # widest=2 -> min bucket 8, capped at G=6
    assert list(idx[0][:2]) == [0, 2] and (idx[0][2:] == 6).all()
    assert (idx[1] == 6).all()


# ---------------------------------------------------------------------------
# engine: parity + lifecycle
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def engine():
    return FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                       max_tenants=4)


def test_engine_bootstrap_steady_and_batch_parity(engine):
    clusters = {f"t{i}": tiny_cluster(10 + i) for i in range(3)}
    res = engine.step([DecideRequest(t, c, int(NOW))
                       for t, c in clusters.items()])
    for (t, c), fd in zip(clusters.items(), res, strict=True):
        assert fd.tenant_id == t and fd.batch_size == 3
        assert_column_parity(fd.arrays, c, NOW, msg=f"bootstrap {t}")
    # steady churn ticks, fresh arrays each tick (engine adopts references)
    rng = np.random.default_rng(5)
    for tick in range(1, 4):
        now = int(NOW) + 60 * tick
        reqs = []
        for i, t in enumerate(clusters):
            c = mutate(tiny_cluster(10 + i), rng)
            clusters[t] = c
            reqs.append(DecideRequest(t, c, now))
        for r, fd in zip(reqs, engine.step(reqs), strict=True):
            assert_column_parity(fd.arrays, r.cluster, now,
                                 msg=f"tick {tick} {r.tenant_id}")
    assert engine.audit() == []


def test_engine_ordered_windows_match_standalone(engine):
    """A draining tenant's ordered follow-up: the selection windows are
    bit-exact vs its standalone ordered decide (arena padding sorts every
    invalid lane behind the windows)."""
    c = tiny_cluster(77)
    c.nodes.tainted[:4] = True
    c.nodes.cordoned[:4] = False
    c.nodes.valid[:8] = True
    fd = engine.step([DecideRequest("drainer", c, int(NOW))])[0]
    assert fd.ordered
    ref = assert_column_parity(fd.arrays, c, NOW, msg="drainer")
    t_off = np.asarray(ref.tainted_offsets)
    u_off = np.asarray(ref.untainted_offsets)
    np.testing.assert_array_equal(
        np.asarray(fd.arrays.tainted_offsets), t_off)
    np.testing.assert_array_equal(
        np.asarray(fd.arrays.untainted_offsets), u_off)
    for g in range(G):
        np.testing.assert_array_equal(
            np.asarray(fd.arrays.untaint_order)[t_off[g]:t_off[g + 1]],
            np.asarray(ref.untaint_order)[t_off[g]:t_off[g + 1]],
            err_msg=f"untaint window g={g}")
        np.testing.assert_array_equal(
            np.asarray(fd.arrays.scale_down_order)[u_off[g]:u_off[g + 1]],
            np.asarray(ref.scale_down_order)[u_off[g]:u_off[g + 1]],
            err_msg=f"scale-down window g={g}")
    np.testing.assert_array_equal(np.asarray(fd.arrays.reap_mask),
                                  np.asarray(ref.reap_mask))


def test_engine_evict_frees_slot_and_rejects_unknown(engine):
    before = engine.tenant_count
    res = engine.step([EvictRequest("t0")])
    assert isinstance(res[0], EvictAck)
    assert engine.tenant_count == before - 1
    res = engine.step([EvictRequest("never-registered")])
    assert isinstance(res[0], TenantError)
    # the slot reuses cleanly: a NEW tenant lands on it with full parity
    c = tiny_cluster(99)
    fd = engine.step([DecideRequest("t0b", c, int(NOW))])[0]
    assert_column_parity(fd.arrays, c, NOW, msg="slot reuse")
    assert engine.audit() == []


def test_engine_invalid_request_does_not_poison_batch(engine):
    good = tiny_cluster(55)
    res = engine.step([
        EvictRequest("ghost-tenant"),
        DecideRequest("survivor", good, int(NOW)),
    ])
    assert isinstance(res[0], TenantError)
    assert_column_parity(res[1].arrays, good, NOW, msg="survivor")


@pytest.mark.slow   # ~26 s of grown-shape compiles; tier-1 keeps grow/compact
                    # parity via the randomized soak (mid-run grows) and
                    # test_engine_compact_during_staged_batch_completes —
                    # the full metric/annotation assertions still run in CI's
                    # unfiltered suite
def test_engine_grow_and_compact():
    from escalator_tpu.metrics import metrics as _m
    from escalator_tpu.observability import RECORDER, resources

    def _ctr(name):
        return _m.registry.get_sample_value(name) or 0.0

    grows0 = _ctr("escalator_tpu_fleet_arena_grow_total")
    compacts0 = _ctr("escalator_tpu_fleet_arena_compact_total")
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=2)
    small = {f"s{i}": tiny_cluster(30 + i) for i in range(2)}
    eng.step([DecideRequest(t, c, int(NOW)) for t, c in small.items()])
    # tenant-axis growth: a third tenant doubles C
    c3 = tiny_cluster(40)
    fd = eng.step([DecideRequest("s2", c3, int(NOW))])[0]
    assert_column_parity(fd.arrays, c3, NOW, msg="slot growth")
    assert eng.buckets["tenants"] == 4
    # round 15: the grow counted, annotated its fleet_batch flight record,
    # and the registered arena owner's bytes == the envelope formula at
    # the NEW buckets
    assert _ctr("escalator_tpu_fleet_arena_grow_total") == grows0 + 1
    # round 16: grows run at PREP time (the pipeline stage that owns the
    # host twins), so the annotation lands on the fleet_prep record
    grow_recs = [r for r in RECORDER.snapshot()
                 if r.get("root") in ("fleet_batch", "fleet_prep")
                 and r.get("fleet_arena_grow")]
    assert grow_recs and "C=4" in grow_recs[-1]["fleet_arena_grow"]
    arena = resources.RESOURCES.snapshot()["fleet_arenas"]
    assert arena["nbytes"] == arena["budget_bytes"] > 0
    # lane/group growth: a tenant bigger than every bucket
    big = representative_cluster(G * 2, P * 4, N * 4, seed=41)
    fd = eng.step([DecideRequest("big", big, int(NOW))])[0]
    assert_column_parity(fd.arrays, big, NOW, msg="lane growth")
    assert eng.buckets["pods"] >= P * 4 and eng.buckets["groups"] >= G * 2
    # pre-growth tenants keep bit-parity afterwards
    c0 = mutate(tiny_cluster(30), np.random.default_rng(6))
    fd = eng.step([DecideRequest("s0", c0, int(NOW) + 60)])[0]
    assert_column_parity(fd.arrays, c0, int(NOW) + 60, msg="post-growth")
    assert eng.audit() == []
    # compact after evictions: slots repack, parity survives
    eng.step([EvictRequest("s1"), EvictRequest("big")])
    info = eng.compact()
    assert info["tenants"] == 2 and info["new_c"] <= info["old_c"]
    assert _ctr("escalator_tpu_fleet_arena_compact_total") == compacts0 + 1
    # compact runs under its own span root, so the annotation reaches a
    # flight record even with no batch in flight
    compact_recs = [r for r in RECORDER.snapshot()
                    if r.get("root") == "fleet_compact"]
    assert compact_recs and compact_recs[-1]["fleet_arena_compact"]
    c0b = mutate(c0, np.random.default_rng(7))
    fd = eng.step([DecideRequest("s0", c0b, int(NOW) + 120)])[0]
    assert_column_parity(fd.arrays, c0b, int(NOW) + 120, msg="post-compact")
    assert eng.audit() == []


def test_engine_recovers_after_dispatch_failure(monkeypatch):
    """A failed _fleet_step dispatch (device error after the arenas were
    donated) must not wedge the engine: the failing batch errors, the
    arenas rebuild, and every tenant re-bootstraps with full parity on its
    next decide."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=2)
    c = tiny_cluster(21)
    eng.step([DecideRequest("phoenix", c, int(NOW))])
    real_step = eng._step_fn

    def boom(*a, **kw):
        raise RuntimeError("injected device failure")

    monkeypatch.setattr(eng, "_step_fn", boom)
    with pytest.raises(RuntimeError, match="injected device failure"):
        eng.step([DecideRequest("phoenix", mutate(
            _copy_cluster(c), np.random.default_rng(1)), int(NOW) + 60)])
    monkeypatch.setattr(eng, "_step_fn", real_step)
    c2 = mutate(_copy_cluster(c), np.random.default_rng(2))
    fd = eng.step([DecideRequest("phoenix", c2, int(NOW) + 120)])[0]
    assert_column_parity(fd.arrays, c2, int(NOW) + 120, msg="post-failure")
    assert eng.audit() == []


def _copy_cluster(c):
    return type(c)(groups=_copy_soa(c.groups), pods=_copy_soa(c.pods),
                   nodes=_copy_soa(c.nodes))


def test_evict_retires_per_tenant_histogram_series():
    from escalator_tpu.observability import histograms

    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=4, flush_ms=1.0)
    try:
        sched.submit("ephemeral", None, 0).result(timeout=10)
        assert histograms.TICKS.peek("fleet/ephemeral") is not None
        sched.evict("ephemeral").result(timeout=10)
        assert histograms.TICKS.peek("fleet/ephemeral") is None
    finally:
        sched.shutdown()


@pytest.mark.parametrize(
    "num_shards",
    [pytest.param(1, marks=pytest.mark.slow), 2,
     pytest.param(4, marks=pytest.mark.slow)])
def test_engine_randomized_multi_tenant_soak(num_shards, monkeypatch):
    """The acceptance soak: randomized per-tick churn over a live fleet
    WITH tenant lifecycle churn (add/evict/grow mid-run); every tenant's
    13 columns bit-identical to its standalone decide — the unsharded
    single-device path — on every tick (for the 1-shard engine and the
    2/4-shard mesh partitions; conftest forces 8 host devices so all
    arms run real shard_map meshes), and the maintained aggregate arenas
    bit-equal to a recompute at the end. The 2-shard arm is the tier-1
    resident; the 1- and 4-shard arms are slow-marked (each re-pays
    every grown-shape compile against the tier-1 870 s budget, and the
    S=1 squeeze path rides every default-engine test in this file) —
    CI's unfiltered suite runs all three."""
    # the soak runs under the armed lock witness: every engine lock is a
    # ranked primitive, and any out-of-rank acquisition anywhere in the
    # churn (grow, evict, digest re-dispatch) fails the test immediately
    # instead of deadlocking it
    monkeypatch.setenv("ESCALATOR_TPU_LOCK_WITNESS", "1")
    witness_base = len(lockwitness.VIOLATIONS)
    from escalator_tpu.observability import provenance

    mismatch_base = provenance.mismatch_total()
    rng = np.random.default_rng(17)
    pyrng = random.Random(17)
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=2, num_shards=num_shards)
    world: dict = {}
    resident: dict = {}
    pyrng18 = random.Random(180 + num_shards)
    next_id = 0
    # 9 ticks is the fewest that still covers ALL lifecycle paths with this
    # seed: 5 registrations, 3 evicts, and one 4x-node-bucket tenant (the
    # mid-run arena grow) — verified by simulating the pyrng consumption
    for tick in range(9):
        now = int(NOW) + 60 * tick
        reqs = []
        # lifecycle churn
        if world and pyrng.random() < 0.25:
            victim = pyrng.choice(sorted(world))
            del world[victim]
            reqs.append(EvictRequest(victim))
        if len(world) < 5 and pyrng.random() < 0.6:
            tid = f"soak{next_id}"
            next_id += 1
            if pyrng.random() < 0.2:
                # a tenant 4x the node bucket: forces an arena grow mid-run
                world[tid] = representative_cluster(
                    G, P, N * 4, seed=100 + next_id)
            else:
                world[tid] = tiny_cluster(100 + next_id)
        # content churn on every live tenant, fresh arrays per tick.
        # round 18: resident tenants randomly ship the churn as a DELTA
        # frame (the streaming-ingestion wire form — changed rows only)
        # instead of a full frame; the parity contract is identical.
        # ``resident`` tracks the content the engine last acknowledged per
        # tenant (the twin the delta applies against); separate rngs so the
        # pre-round-18 lifecycle draw sequence is untouched.
        for tid in sorted(world):
            c = world[tid]
            fresh = type(c)(groups=_copy_soa(c.groups),
                            pods=_copy_soa(c.pods),
                            nodes=_copy_soa(c.nodes))
            world[tid] = mutate(fresh, rng)
            prev = resident.get(tid)
            if (prev is not None and pyrng18.random() < 0.5
                    and _shapes_of(prev) == _shapes_of(world[tid])):
                reqs.append(DecideRequest(
                    tid, None, now, delta=_delta_from(prev, world[tid])))
            else:
                reqs.append(DecideRequest(tid, world[tid], now))
        results = eng.step(reqs)
        for r, res in zip(reqs, results, strict=True):
            if isinstance(r, EvictRequest):
                assert isinstance(res, EvictAck)
                resident.pop(r.tenant_id, None)
            else:
                assert_column_parity(res.arrays, world[r.tenant_id], now,
                                     msg=f"soak tick {tick} {r.tenant_id}")
                resident[r.tenant_id] = world[r.tenant_id]
        # round-18 digest fast path: re-ask every tenant the SAME question
        # (a repeated full frame or an empty delta) at the same now — the
        # answer must be bit-equal to this tick's dispatch whether it came
        # from the cache or (chaos-forced miss on tick 4) a re-dispatch
        if pyrng18.random() < 0.8:
            if tick == 4:
                from escalator_tpu.chaos import CHAOS

                CHAOS.arm("fleet_digest", times=1)
            try:
                reqs2, expect = [], []
                for r, res in zip(reqs, results, strict=True):
                    if isinstance(r, EvictRequest):
                        continue
                    tid = r.tenant_id
                    if pyrng18.random() < 0.5:
                        reqs2.append(DecideRequest(tid, world[tid], now))
                    else:
                        reqs2.append(DecideRequest(
                            tid, None, now,
                            delta=_delta_from(world[tid], world[tid])))
                    expect.append(res)
                results2 = eng.step(reqs2)
                for res2, res1 in zip(results2, expect, strict=True):
                    for f in kernel.GROUP_DECISION_FIELDS:
                        np.testing.assert_array_equal(
                            np.asarray(getattr(res2.arrays, f)),
                            np.asarray(getattr(res1.arrays, f)),
                            err_msg=f"cached tick {tick} "
                                    f"{res1.tenant_id}:{f}")
                # round 19: a digest-served answer must EXPLAIN exactly
                # like a dispatched one — the re-derived calculus
                # bit-cross-checks against the cached columns the tenant
                # was actually served (ticks >= 7 bound the explain
                # kernel's compile to the final grown arena shape)
                if tick >= 7:
                    for res2 in results2:
                        if not res2.cached:
                            continue
                        docs = eng.explain_tenant(res2.tenant_id)
                        st = np.asarray(res2.arrays.status)
                        nd = np.asarray(res2.arrays.nodes_delta)
                        for d in docs:
                            assert "mismatches" not in d, \
                                f"tick {tick} {res2.tenant_id}: {d}"
                            g = d["group"]
                            assert d["status"] == int(st[g])
                            assert d["nodes_delta"] == int(nd[g])
                    assert provenance.mismatch_total() == mismatch_base
            finally:
                if tick == 4:
                    CHAOS.disarm("fleet_digest")
    assert eng.audit() == [], "maintained fleet aggregates diverged"
    assert eng.cache_hits > 0, "the soak never exercised the digest cache"
    assert lockwitness.VIOLATIONS[witness_base:] == [], \
        "the soak tripped the lock-order witness"


def _copy_soa(soa):
    from dataclasses import fields

    return type(soa)(**{f.name: np.array(getattr(soa, f.name))
                        for f in fields(soa)})


def _shapes_of(cluster) -> tuple:
    return (int(cluster.groups.valid.shape[0]),
            int(cluster.pods.valid.shape[0]),
            int(cluster.nodes.valid.shape[0]))


def _delta_from(prev, new) -> "service_mod.DeltaFrame":
    """The delta frame a streaming client would ship for prev -> new: the
    positional diff's changed rows per section, groups riding along only
    when the options changed (prev is new -> an EMPTY delta, the digest
    fast path's no-op form)."""
    from dataclasses import fields

    def take(soa, idx):
        return type(soa)(**{f.name: np.asarray(getattr(soa, f.name))[idx]
                            for f in fields(soa)})

    pidx = service_mod._changed_rows(prev.pods, new.pods)
    nidx = service_mod._changed_rows(prev.nodes, new.nodes)
    groups_changed = (prev is not new and
                      len(service_mod._changed_rows(prev.groups,
                                                    new.groups)) > 0)
    return service_mod.DeltaFrame(
        shapes=_shapes_of(new),
        pod_idx=pidx.astype(np.int32), pod_vals=take(new.pods, pidx),
        node_idx=nidx.astype(np.int32), node_vals=take(new.nodes, nidx),
        groups=new.groups if groups_changed else None)


# ---------------------------------------------------------------------------
# digest fast path (round 18): hits, and every invalidation edge
# ---------------------------------------------------------------------------


def _assert_bit_equal(a, b, msg=""):
    from dataclasses import fields

    for f in fields(a):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f.name)), np.asarray(getattr(b, f.name)),
            err_msg=f"{msg}:{f.name}")


def test_engine_digest_cache_hit_serves_bit_equal_columns():
    """An unchanged request (same content, same now) answers from the
    tenant's cached decision columns: cached=True, batch_size=0 (it rode
    no micro-batch), arrays bit-equal to the dispatch that populated the
    cache AND to a standalone decide. A new now misses; an EMPTY delta
    frame at the cached now hits."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=4)
    c = tiny_cluster(70)
    r1 = eng.step([DecideRequest("dig", c, int(NOW))])[0]
    assert not r1.cached and r1.batch_size == 1
    r2 = eng.step([DecideRequest("dig", _copy_cluster(c), int(NOW))])[0]
    assert r2.cached and r2.batch_size == 0 and eng.cache_hits == 1
    _assert_bit_equal(r2.arrays, r1.arrays, "cached-vs-dispatch")
    assert_column_parity(r2.arrays, c, NOW, msg="cached-vs-standalone")
    # same content at a NEW now: decisions are now-dependent -> miss
    r3 = eng.step([DecideRequest("dig", _copy_cluster(c), int(NOW) + 60)])[0]
    assert not r3.cached and eng.cache_hits == 1
    # empty delta at the (new) cached now: the streaming no-op form -> hit
    r4 = eng.step([DecideRequest("dig", None, int(NOW) + 60,
                                 delta=_delta_from(c, c))])[0]
    assert r4.cached and eng.cache_hits == 2
    _assert_bit_equal(r4.arrays, r3.arrays, "empty-delta-hit")
    # a NON-empty delta never hits, and its answer reflects the change
    c2 = _copy_cluster(c)
    c2.pods.cpu_milli[3] += 500
    r5 = eng.step([DecideRequest("dig", None, int(NOW) + 60,
                                 delta=_delta_from(c, c2))])[0]
    assert not r5.cached
    assert_column_parity(r5.arrays, c2, int(NOW) + 60, msg="delta-churn")
    assert eng.audit() == []


def test_engine_digest_cache_evict_reregister_and_group_reload_miss():
    """Invalidation edges that must NEVER serve stale columns: a tenant
    evicted and re-registered under the same id starts cold (its cache
    died with the registration), and a delta frame carrying a groups
    section (set_groups/options reload) misses even when every group
    value is identical — the reload is a semantic barrier."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=4)
    c = tiny_cluster(71)
    eng.step([DecideRequest("ev", c, int(NOW))])
    assert eng.step([DecideRequest("ev", _copy_cluster(c),
                                   int(NOW))])[0].cached
    # evict -> re-register same id, same content, same now
    assert isinstance(eng.step([EvictRequest("ev")])[0], EvictAck)
    r = eng.step([DecideRequest("ev", _copy_cluster(c), int(NOW))])[0]
    assert not r.cached, "stale columns served across evict/re-register"
    assert_column_parity(r.arrays, c, NOW, msg="re-register")
    # group-options reload: an otherwise-empty delta WITH a groups section
    hits = eng.cache_hits
    reload_frame = _delta_from(c, _copy_cluster(c))
    reload_frame = service_mod.DeltaFrame(
        shapes=reload_frame.shapes, pod_idx=reload_frame.pod_idx,
        pod_vals=reload_frame.pod_vals, node_idx=reload_frame.node_idx,
        node_vals=reload_frame.node_vals, groups=_copy_soa(c.groups))
    r = eng.step([DecideRequest("ev", None, int(NOW),
                                delta=reload_frame)])[0]
    assert not r.cached and eng.cache_hits == hits
    assert_column_parity(r.arrays, c, NOW, msg="group-reload")
    # after the reload dispatched, the no-op form hits again
    assert eng.step([DecideRequest("ev", None, int(NOW),
                                   delta=_delta_from(c, c))])[0].cached


def test_engine_digest_cache_chaos_site_forces_miss_bit_equal():
    """The ``fleet_digest`` chaos site fires between the digest check and
    the answer: the request must ride the micro-batch (a full dispatch)
    and produce EXACTLY the columns the cache would have served."""
    from escalator_tpu.chaos import CHAOS

    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=4)
    c = tiny_cluster(72)
    r1 = eng.step([DecideRequest("chz", c, int(NOW))])[0]
    CHAOS.arm("fleet_digest", times=1)
    try:
        r2 = eng.step([DecideRequest("chz", _copy_cluster(c), int(NOW))])[0]
        assert not r2.cached and r2.batch_size == 1, \
            "chaos-armed digest check still answered from cache"
    finally:
        CHAOS.disarm("fleet_digest")
    _assert_bit_equal(r2.arrays, r1.arrays, "chaos-miss-vs-cache")
    # the rule consumed itself: the next repeat hits again
    assert eng.step([DecideRequest("chz", _copy_cluster(c),
                                   int(NOW))])[0].cached


def test_engine_digest_cache_grow_and_compact_invalidate():
    """Arena reshapes between a cache write and the next probe: a tenant-
    axis grow and a compact both bump the epoch — the probe must miss
    (the cached columns predate the reshape) and the re-dispatch must
    stay parity-exact. C-axis growth only (the lane-growth compiles live
    in the slow-marked grow test)."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=2)
    c = tiny_cluster(73)
    eng.step([DecideRequest("gc0", c, int(NOW))])
    assert eng.step([DecideRequest("gc0", _copy_cluster(c),
                                   int(NOW))])[0].cached
    # third tenant doubles the tenant axis: epoch bump
    eng.step([DecideRequest("gc1", tiny_cluster(74), int(NOW)),
              DecideRequest("gc2", tiny_cluster(75), int(NOW))])
    r = eng.step([DecideRequest("gc0", _copy_cluster(c), int(NOW))])[0]
    assert not r.cached, "stale columns served across an arena grow"
    assert_column_parity(r.arrays, c, NOW, msg="post-grow")
    assert eng.step([DecideRequest("gc0", _copy_cluster(c),
                                   int(NOW))])[0].cached
    # compact after evictions: epoch bump again
    eng.step([EvictRequest("gc1"), EvictRequest("gc2")])
    eng.compact()
    r = eng.step([DecideRequest("gc0", _copy_cluster(c), int(NOW))])[0]
    assert not r.cached, "stale columns served across a compact"
    assert_column_parity(r.arrays, c, NOW, msg="post-compact")
    assert eng.audit() == []


def _copy_cluster(c):
    return type(c)(groups=_copy_soa(c.groups), pods=_copy_soa(c.pods),
                   nodes=_copy_soa(c.nodes))


# ---------------------------------------------------------------------------
# sharded engine (round 16): parity, balance, and the concurrency contract
# ---------------------------------------------------------------------------


def test_engine_sharded_parity_and_balance():
    """A 2-shard engine: tenants spread across both mesh rows, every
    decision bit-identical to the standalone (unsharded) decide, the
    FleetDecision carries its shard, and the maintained arenas audit
    clean across shards."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=4, num_shards=2)
    assert eng.shards == 2 and eng.buckets["shards"] == 2
    clusters = {f"sh{i}": tiny_cluster(200 + i) for i in range(4)}
    res = eng.step([DecideRequest(t, c, int(NOW))
                    for t, c in clusters.items()])
    shards_used = set()
    for (t, c), fd in zip(clusters.items(), res, strict=True):
        assert_column_parity(fd.arrays, c, NOW, msg=f"sharded {t}")
        assert fd.shard == eng.shard_of(t)
        shards_used.add(fd.shard)
    assert shards_used == {0, 1}, "tenants did not balance across shards"
    # steady tick with churn, still bit-exact per tenant
    rng = np.random.default_rng(8)
    reqs = []
    for i, t in enumerate(clusters):
        clusters[t] = mutate(tiny_cluster(200 + i), rng)
        reqs.append(DecideRequest(t, clusters[t], int(NOW) + 60))
    for r, fd in zip(reqs, eng.step(reqs), strict=True):
        assert_column_parity(fd.arrays, r.cluster, int(NOW) + 60,
                             msg=f"sharded tick {r.tenant_id}")
    assert eng.audit() == []


@pytest.mark.slow
def test_engine_grow_during_staged_batch_completes():
    """Regression (round-16 pipeline): a prepare that needs an arena grow
    while ANOTHER batch is staged must wait for that batch to drain —
    and must NOT deadlock against the execute that drains it (the drain
    wait releases the host condition)."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=4)
    c_a = tiny_cluster(300)
    eng.step([DecideRequest("a", c_a, int(NOW))])
    c_a2 = mutate(_copy_cluster(c_a), np.random.default_rng(3))
    pb_a = eng.prepare_batch([DecideRequest("a", c_a2, int(NOW) + 60)])
    # outgrows only the NODE bucket: one grown-shape compile, not three
    big = representative_cluster(G, P, N * 2, seed=301)
    done = {}

    def grow_then_decide():
        # prepare of this batch needs a lane-bucket grow -> staged drain
        done["b"] = eng.step([DecideRequest("b", big, int(NOW) + 60)])[0]

    th = threading.Thread(target=grow_then_decide, daemon=True)
    th.start()
    time.sleep(0.3)   # let the grow reach the drain wait
    res_a = eng.execute_batch(pb_a)
    th.join(timeout=30)
    assert not th.is_alive(), "grow-during-staged deadlocked"
    assert_column_parity(res_a[0].arrays, c_a2, int(NOW) + 60, msg="staged a")
    assert_column_parity(done["b"].arrays, big, int(NOW) + 60, msg="grown b")
    assert eng.audit() == []


def test_engine_compact_during_staged_batch_completes():
    """Regression: compact() while a batch is staged must wait for it
    WITHOUT holding the execute lock — holding it would deadlock against
    the execute that drains the staged batch."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=4, num_shards=2)
    cs = {t: tiny_cluster(400 + i) for i, t in enumerate(("ca", "cb", "cc"))}
    eng.step([DecideRequest(t, c, int(NOW)) for t, c in cs.items()])
    eng.step([EvictRequest("cc")])
    c2 = mutate(_copy_cluster(cs["ca"]), np.random.default_rng(9))
    pb = eng.prepare_batch([DecideRequest("ca", c2, int(NOW) + 60)])
    done = {}

    def compacting():
        done["info"] = eng.compact()

    th = threading.Thread(target=compacting, daemon=True)
    th.start()
    time.sleep(0.3)   # compact reaches the staged-drain wait
    res = eng.execute_batch(pb)
    th.join(timeout=30)
    assert not th.is_alive(), "compact-during-staged deadlocked"
    assert done["info"]["tenants"] == 2
    assert_column_parity(res[0].arrays, c2, int(NOW) + 60, msg="staged ca")
    # post-compact parity on a repacked tenant
    c3 = mutate(_copy_cluster(cs["cb"]), np.random.default_rng(10))
    fd = eng.step([DecideRequest("cb", c3, int(NOW) + 120)])[0]
    assert_column_parity(fd.arrays, c3, int(NOW) + 120, msg="post-compact cb")
    assert eng.audit() == []


def test_engine_stale_prepared_batch_is_discarded_not_rerun():
    """Regression (review finding): a prepared batch whose epoch fell
    behind (dispatch-failure rebuild) must FAIL with StaleBatchError —
    re-preparing from the execute path would race the prep thread and
    desync twins from the arenas. The engine stays serviceable after."""
    from escalator_tpu.fleet import StaleBatchError

    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=2)
    c = tiny_cluster(330)
    eng.step([DecideRequest("st", c, int(NOW))])
    c2 = mutate(_copy_cluster(c), np.random.default_rng(11))
    pb = eng.prepare_batch([DecideRequest("st", c2, int(NOW) + 60)])
    # simulate the dispatch-failure recovery the real path runs: epoch
    # bump + wholesale twin reset (the only way a staged batch goes stale)
    with eng._host:
        eng._epoch += 1
        for t in eng._tenants.values():
            t.pods = service_mod._empty_pods(eng._P)
            t.nodes = service_mod._empty_nodes(eng._N)
            t.groups = service_mod._empty_groups(eng._G)
            t.dirty = np.ones(eng._G, bool)
    with pytest.raises(StaleBatchError):
        eng.execute_batch(pb)
    # the staged registration cleared (reshapes would not wait forever)
    assert eng._staged is None
    # and a resubmit serves with full parity against the rebuilt twins
    fd = eng.step([DecideRequest("st", _copy_cluster(c2), int(NOW) + 60)])[0]
    assert_column_parity(fd.arrays, c2, int(NOW) + 60, msg="post-stale")
    assert eng.audit() == []


def test_engine_release_prepared_rolls_back_twins():
    """Regression: an abandoned prepared batch must unwind its twin
    adoption — otherwise the tenant's next diff skips the lanes the
    device never received and parity breaks silently."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=2)
    c = tiny_cluster(310)
    eng.step([DecideRequest("rb", c, int(NOW))])
    c2 = mutate(_copy_cluster(c), np.random.default_rng(4))
    pb = eng.prepare_batch([DecideRequest("rb", c2, int(NOW) + 60)])
    assert eng.release_prepared(pb) is True
    # re-submitting the same content must re-diff from the OLD twin
    fd = eng.step([DecideRequest("rb", _copy_cluster(c2), int(NOW) + 60)])[0]
    assert_column_parity(fd.arrays, c2, int(NOW) + 60, msg="post-release")
    # an abandoned REGISTRATION unwinds too (tenant never reaches the device)
    pb2 = eng.prepare_batch(
        [DecideRequest("ghost", tiny_cluster(311), int(NOW))])
    assert eng.release_prepared(pb2) is True
    assert not eng.has_tenant("ghost")
    # an abandoned EVICT resurrects the tenant
    pb3 = eng.prepare_batch([EvictRequest("rb")])
    assert not eng.has_tenant("rb")
    assert eng.release_prepared(pb3) is True
    assert eng.has_tenant("rb")
    c3 = mutate(_copy_cluster(c2), np.random.default_rng(5))
    fd = eng.step([DecideRequest("rb", c3, int(NOW) + 120)])[0]
    assert_column_parity(fd.arrays, c3, int(NOW) + 120, msg="post-evict-rb")


def test_engine_prepare_failure_rolls_back_twins(monkeypatch):
    """Regression (review finding): a NON-TenantError escaping partway
    through prepare_batch (a device error inside a register-grow, an
    assembly failure) must unwind every already-adopted entry — evicted
    tenants resurrect, registrations drop, twin adoptions roll back —
    instead of leaving the engine permanently desynced from the arenas."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=4)
    ca, cb = tiny_cluster(340), tiny_cluster(341)
    eng.step([DecideRequest("pa", ca, int(NOW)),
              DecideRequest("pb", cb, int(NOW))])
    monkeypatch.setattr(eng, "_assemble",
                        lambda entries: (_ for _ in ()).throw(
                            RuntimeError("injected assembly failure")))
    c2 = mutate(_copy_cluster(ca), np.random.default_rng(6))
    with pytest.raises(RuntimeError, match="injected"):
        eng.prepare_batch([DecideRequest("pa", c2, int(NOW) + 60),
                           EvictRequest("pb"),
                           DecideRequest("pnew", tiny_cluster(342),
                                         int(NOW) + 60)])
    # evict rolled back (tenant resurrected), registration dropped
    assert eng.has_tenant("pb") and not eng.has_tenant("pnew")
    assert eng._staged is None
    monkeypatch.undo()
    # twins re-diff from the PRE-failure content with full parity
    fd = eng.step([DecideRequest("pa", _copy_cluster(c2),
                                 int(NOW) + 60)])[0]
    assert_column_parity(fd.arrays, c2, int(NOW) + 60, msg="post-prep-fail")
    c3 = mutate(_copy_cluster(cb), np.random.default_rng(7))
    fd = eng.step([DecideRequest("pb", c3, int(NOW) + 120)])[0]
    assert_column_parity(fd.arrays, c3, int(NOW) + 120,
                         msg="post-prep-fail pb")
    assert eng.audit() == []
    assert eng.audit() == []


def test_engine_release_waits_for_inflight_execute(monkeypatch):
    """Regression: release of a staged batch while an EARLIER batch's
    execute is in flight must wait for the engine (bounded) before
    rolling back, not race the dispatch."""
    eng = FleetEngine(num_groups=G, pod_capacity=P, node_capacity=N,
                      max_tenants=2)
    c = tiny_cluster(320)
    eng.step([DecideRequest("slow", c, int(NOW))])
    real_step = eng._step_fn

    def slow_step(*a, **kw):
        time.sleep(0.5)
        return real_step(*a, **kw)

    monkeypatch.setattr(eng, "_step_fn", slow_step)
    c2 = mutate(_copy_cluster(c), np.random.default_rng(6))
    results = {}

    def run_a():
        results["a"] = eng.step(
            [DecideRequest("slow", c2, int(NOW) + 60)])[0]

    th = threading.Thread(target=run_a, daemon=True)
    th.start()
    time.sleep(0.15)   # batch A inside the slow dispatch
    c_b = tiny_cluster(321)
    pb_b = eng.prepare_batch([DecideRequest("other", c_b, int(NOW) + 60)])
    t0 = time.monotonic()
    assert eng.release_prepared(pb_b, wait_sec=10.0) is True
    assert time.monotonic() - t0 > 0.1, "release did not wait for execute"
    th.join(timeout=30)
    assert_column_parity(results["a"].arrays, c2, int(NOW) + 60, msg="slow a")
    assert not eng.has_tenant("other")
    monkeypatch.setattr(eng, "_step_fn", real_step)
    fd = eng.step([DecideRequest("other", c_b, int(NOW) + 120)])[0]
    assert_column_parity(fd.arrays, c_b, int(NOW) + 120, msg="other after")
    assert eng.audit() == []


# ---------------------------------------------------------------------------
# scheduler semantics (fake engine: admission logic needs no device)
# ---------------------------------------------------------------------------


class _FakeEngine:
    def __init__(self):
        self.batches = []
        self.tenants = set()
        self.block = threading.Event()
        self.block.set()

    @property
    def tenant_count(self):
        return len(self.tenants)

    def has_tenant(self, tid):
        return tid in self.tenants

    def step(self, requests):
        self.block.wait(timeout=10)
        self.batches.append([r.tenant_id for r in requests])
        out = []
        for r in requests:
            if isinstance(r, EvictRequest):
                self.tenants.discard(r.tenant_id)
                out.append(EvictAck(r.tenant_id))
            else:
                self.tenants.add(r.tenant_id)
                out.append(("decided", r.tenant_id, r.now_sec))
        return out


def test_scheduler_validates_tenant_ids_before_queueing():
    sched = FleetScheduler(_FakeEngine(), flush_ms=1.0)
    try:
        for bad in ("", "x" * 300, None, 7, "bad\x00id"):
            with pytest.raises(TenantError):
                sched.submit(bad, None, 0)
        assert sched.admitted_total == 0 and sched.queue_depth == 0
    finally:
        sched.shutdown()
    assert validate_tenant_id("ok-tenant") == "ok-tenant"


def test_scheduler_coalescing_and_oldest_first_fairness():
    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=8, flush_ms=20.0, queue_limit=64,
                           per_tenant_inflight=4)
    try:
        sched.pause()
        futs = [sched.submit(f"c{i}", None, i) for i in range(4)]
        # two requests from one tenant: the second must ride the NEXT batch
        futs.append(sched.submit("c0", None, 99))
        assert sched.oldest_waiting_sec() > 0
        sched.resume()
        results = [f.result(timeout=10) for f in futs]
        assert [r[1] for r in results[:4]] == [f"c{i}" for i in range(4)]
        assert len(eng.batches) == 2, eng.batches
        assert eng.batches[0] == ["c0", "c1", "c2", "c3"]  # oldest-first
        assert eng.batches[1] == ["c0"]                    # the dup, next batch
    finally:
        sched.shutdown()


def test_scheduler_noop_shaped_requests_are_slot_free():
    """Empty-delta requests (the streaming twin's idle shape) must not
    count against max_batch: a backlog of 6 no-ops + 2 real requests
    drains in ONE flush at max_batch=2, not ⌈8/2⌉ — the digest fast
    path's throughput depends on idle requests riding the take for
    free (round 18)."""
    c = tiny_cluster(93)
    noop = _delta_from(c, c)
    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=2, flush_ms=20.0, queue_limit=64)
    try:
        sched.pause()
        futs = [sched.submit(f"real{i}", c, 0) for i in range(2)]
        futs += [sched.submit(f"idle{i}", None, 0, delta=noop)
                 for i in range(6)]
        sched.resume()
        for f in futs:
            f.result(timeout=10)
        assert len(eng.batches) == 1, eng.batches
        assert len(eng.batches[0]) == 8
    finally:
        sched.shutdown()


def test_scheduler_backpressure_and_per_tenant_cap():
    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=4, flush_ms=5.0, queue_limit=3,
                           per_tenant_inflight=1)
    try:
        sched.pause()
        sched.submit("a", None, 0)
        with pytest.raises(AdmissionError) as ei:
            sched.submit("a", None, 1)
        assert ei.value.reason == "tenant-inflight"
        sched.submit("b", None, 0)
        sched.submit("c", None, 0)
        with pytest.raises(AdmissionError) as ei:
            sched.submit("d", None, 0)
        assert ei.value.reason == "queue-full"
        assert ei.value.retry_after_ms > 0
        assert sched.rejected_total == 2 and sched.admitted_total == 3
        sched.resume()
    finally:
        sched.shutdown()


def test_scheduler_records_per_tenant_latency_series():
    from escalator_tpu.observability import histograms

    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=4, flush_ms=1.0)
    try:
        sched.submit("latency-tenant", None, 0).result(timeout=10)
        h = histograms.TICKS.peek("fleet/latency-tenant")
        assert h is not None and h.count >= 1
        # the tenant-labeled root rides the same export as tick roots
        assert any(key == ("fleet/latency-tenant",)
                   for key, _ in histograms.TICKS.items())
    finally:
        sched.shutdown()


def test_scheduler_engine_failure_fails_batch_not_process():
    class _Boom(_FakeEngine):
        def step(self, requests):
            raise RuntimeError("device on fire")

    sched = FleetScheduler(_Boom(), flush_ms=1.0)
    try:
        fut = sched.submit("t", None, 0)
        with pytest.raises(RuntimeError, match="device on fire"):
            fut.result(timeout=10)
        # the worker survives and serves the next batch
        ok = FleetScheduler(_FakeEngine(), flush_ms=1.0)
        try:
            assert ok.submit("t", None, 0).result(timeout=10)[0] == "decided"
        finally:
            ok.shutdown()
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# round 16: weighted-fair classes, SLO admission, pipelined scheduler
# ---------------------------------------------------------------------------


class _FakeTwoStage(_FakeEngine):
    """Fake engine exposing the two-stage prepare/execute API (so the
    scheduler runs its pipelined worker pair) with injectable delays."""

    def __init__(self, prep_sec: float = 0.0, exec_sec: float = 0.0):
        super().__init__()
        self.prep_sec = prep_sec
        self.exec_sec = exec_sec
        self.executed_pbs = []
        self.released_pbs = []

    def prepare_batch(self, requests):
        if self.prep_sec:
            time.sleep(self.prep_sec)
        return types.SimpleNamespace(
            requests=list(requests), overlap_saved_ms=None,
            prep_ms=self.prep_sec * 1e3)

    def execute_batch(self, pb):
        if self.exec_sec:
            time.sleep(self.exec_sec)
        self.executed_pbs.append(pb)
        return super().step(pb.requests)

    def release_prepared(self, pb, wait_sec: float = 5.0):
        self.released_pbs.append(pb)
        return True


def test_scheduler_weighted_fair_class_shares():
    """Saturated queues in all three default classes: one batch's slots
    split 4/2/1 (critical/standard/batch at max_batch=7), oldest-first
    within each class."""
    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=7, flush_ms=30.0, queue_limit=64,
                           per_tenant_inflight=4)
    try:
        sched.pause()
        for i in range(8):
            sched.submit(f"crit{i}", None, i, klass="critical")
        for i in range(8):
            sched.submit(f"std{i}", None, i, klass="standard")
        for i in range(8):
            sched.submit(f"bat{i}", None, i, klass="batch")
        sched.resume()
        deadline = time.monotonic() + 10
        while not eng.batches and time.monotonic() < deadline:
            time.sleep(0.01)
        first = eng.batches[0]
        assert len(first) == 7, first
        counts = {p: sum(1 for t in first if t.startswith(p))
                  for p in ("crit", "std", "bat")}
        assert counts == {"crit": 4, "std": 2, "bat": 1}, first
        # within a class: oldest-first
        assert [t for t in first if t.startswith("crit")] == [
            f"crit{i}" for i in range(4)]
        st = sched.stats()
        assert st["classes"]["critical"]["weight"] == 4
    finally:
        sched.shutdown()


def test_scheduler_small_batch_does_not_starve_lightest_class():
    """Regression (review finding): with max_batch smaller than the
    active-class count, heaviest-first quotas would starve the lightest
    class — assembly falls back to oldest-first, so a batch-class
    request admitted first is served first."""
    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=2, flush_ms=20.0, queue_limit=64,
                           per_tenant_inflight=4)
    try:
        sched.pause()
        f_b = sched.submit("bulk", None, 0, klass="batch")   # oldest
        sched.submit("c1", None, 1, klass="critical")
        sched.submit("c2", None, 2, klass="critical")
        sched.submit("s1", None, 3, klass="standard")
        sched.resume()
        assert f_b.result(timeout=10)[0] == "decided"
        # the oldest (batch-class) request rode the FIRST batch
        assert "bulk" in eng.batches[0], eng.batches
    finally:
        sched.shutdown()


def test_scheduler_chatty_tenant_bounded_head_of_line():
    """Adversarial arrivals: one chatty tenant floods the queue ahead of
    three trickle tenants — one-per-tenant batching keeps the trickle
    tenants in the FIRST batch, and the skipped chatty requests count the
    deferred counter while keeping their queue positions."""
    from escalator_tpu.metrics import metrics as _m

    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=4, flush_ms=20.0, queue_limit=64,
                           per_tenant_inflight=16)
    try:
        sched.pause()
        for i in range(10):
            sched.submit("chatty", None, i)
        for t in ("t1", "t2", "t3"):
            sched.submit(t, None, 0)
        d0 = _m.registry.get_sample_value(
            "escalator_tpu_fleet_batch_deferred_total") or 0.0
        sched.resume()
        deadline = time.monotonic() + 10
        while len(eng.batches) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.batches[0] == ["chatty", "t1", "t2", "t3"], eng.batches
        for b in eng.batches:
            assert b.count("chatty") <= 1
        assert sched.deferred_total > 0
        assert (_m.registry.get_sample_value(
            "escalator_tpu_fleet_batch_deferred_total") or 0.0) > d0
    finally:
        sched.shutdown()


def test_scheduler_class_queue_share_cap():
    """The batch class may hold at most queue_share x queue_limit slots —
    overflow rejects with the class-specific reason while the global
    queue still has room."""
    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=4, flush_ms=50.0, queue_limit=8,
                           per_tenant_inflight=1)
    try:
        sched.pause()
        for i in range(4):   # 8 * 0.5 = 4 slots for the batch class
            sched.submit(f"b{i}", None, 0, klass="batch")
        with pytest.raises(AdmissionError) as ei:
            sched.submit("b-overflow", None, 0, klass="batch")
        assert ei.value.reason == "queue-full-batch"
        # other classes are unaffected by the batch-class cap
        sched.submit("still-fine", None, 0, klass="critical")
    finally:
        sched.shutdown()


def test_scheduler_retry_after_scales_with_inflight_depth():
    """Satellite: a tenant-inflight rejection's retry-after reflects the
    tenant's own depth (its requests ride SEPARATE batches) plus the
    queue backlog — not the old flat one-flush-interval floor."""
    eng = _FakeEngine()
    flush_ms = 10.0
    sched = FleetScheduler(eng, max_batch=4, flush_ms=flush_ms,
                           queue_limit=64, per_tenant_inflight=3)
    try:
        sched.pause()
        for i in range(3):
            sched.submit("deep", None, i)
        with pytest.raises(AdmissionError) as ei:
            sched.submit("deep", None, 9)
        assert ei.value.reason == "tenant-inflight"
        first = ei.value.retry_after_ms
        assert first >= 3 * flush_ms, first   # depth 3 -> >= 3 intervals
        # a deeper queue pushes the estimate further out
        for i in range(20):
            sched.submit(f"fill{i}", None, i)
        with pytest.raises(AdmissionError) as ei:
            sched.submit("deep", None, 10)
        assert ei.value.retry_after_ms > first
    finally:
        sched.shutdown()


def test_scheduler_class_p99_breach_counter():
    """A class whose measured p99 exceeds its declared target counts
    breaches (checked on the served-request cadence) into both the
    scheduler stats and the Prometheus counter."""
    from escalator_tpu.fleet import PriorityClass
    from escalator_tpu.metrics import metrics as _m
    from escalator_tpu.observability import histograms

    histograms.TICKS.discard("fleet/class/sla-tight")
    eng = _FakeEngine()
    sched = FleetScheduler(
        eng, max_batch=8, flush_ms=1.0, queue_limit=64,
        per_tenant_inflight=64,
        classes=(PriorityClass("sla-tight", weight=1,
                               p99_target_ms=0.0001),),
        default_class="sla-tight")
    try:
        b0 = _m.registry.get_sample_value(
            "escalator_tpu_fleet_class_p99_breach_total",
            {"klass": "sla-tight"}) or 0.0
        futs = [sched.submit(f"t{i}", None, 0) for i in range(20)]
        for f in futs:
            f.result(timeout=10)
        assert sched.class_breaches["sla-tight"] >= 1
        st = sched.stats()["classes"]["sla-tight"]
        assert st["breaches"] >= 1
        assert st["p99_ms"] is not None and st["p99_ms"] > st["p99_target_ms"]
        assert (_m.registry.get_sample_value(
            "escalator_tpu_fleet_class_p99_breach_total",
            {"klass": "sla-tight"}) or 0.0) > b0
    finally:
        sched.shutdown()
        histograms.TICKS.discard("fleet/class/sla-tight")


def test_scheduler_class_breach_counter_recovers():
    """Regression (review finding): the breach check reads a ROLLING
    window, not the lifetime series — one slow episode must stop counting
    breaches once the recent window is healthy again (a lifetime p99
    would pin the counter climbing for ~100x as many good samples)."""
    from escalator_tpu.fleet import PriorityClass
    from escalator_tpu.observability import histograms

    histograms.TICKS.discard("fleet/class/sla-win")
    eng = _FakeEngine()
    sched = FleetScheduler(
        eng, max_batch=16, flush_ms=1.0, queue_limit=128,
        per_tenant_inflight=64,
        classes=(PriorityClass("sla-win", weight=1, p99_target_ms=60.0),),
        default_class="sla-win")
    try:
        # slow episode: one full check window held >> target in the queue
        sched.pause()
        futs = [sched.submit(f"s{i}", None, 0) for i in range(16)]
        time.sleep(0.2)
        sched.resume()
        for f in futs:
            f.result(timeout=10)
        deadline = time.monotonic() + 5
        while not sched.class_breaches["sla-win"] and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        breached = sched.class_breaches["sla-win"]
        assert breached >= 1
        # recovery: fast windows only — the counter must go quiet even
        # though the LIFETIME p99 still sits far above the 60 ms target
        for r in range(3):
            futs = [sched.submit(f"f{r}x{i}", None, 0) for i in range(16)]
            for f in futs:
                f.result(timeout=10)
        assert sched.class_breaches["sla-win"] == breached
    finally:
        sched.shutdown()
        histograms.TICKS.discard("fleet/class/sla-win")


def test_scheduler_evict_inherits_lightest_queued_class():
    """Regression (resurrection bug): an evict must not ride a heavier
    class than the tenant's queued decides — it inherits the LIGHTEST
    queued class so it can never dispatch before them."""
    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=8, flush_ms=20.0, queue_limit=64,
                           per_tenant_inflight=4)
    try:
        sched.submit("victim", None, 0).result(timeout=10)   # registers
        sched.pause()
        f_dec = sched.submit("victim", None, 1, klass="batch")
        f_ev = sched.evict("victim")
        with sched._cv:
            klasses = [p.klass for p in sched._queues["batch"]]
        assert len(klasses) == 2, "evict did not inherit the batch class"
        sched.resume()
        assert f_dec.result(timeout=10)[0] == "decided"
        assert isinstance(f_ev.result(timeout=10), EvictAck)
        assert not eng.has_tenant("victim")
    finally:
        sched.shutdown()


def test_scheduler_pipelined_overlap_accounting():
    """The pipelined worker pair: batch k+1's prep runs while batch k's
    execute is in flight, and the prepared batch carries a positive
    overlap_saved_ms measured against the dispatch windows."""
    eng = _FakeTwoStage(prep_sec=0.03, exec_sec=0.08)
    sched = FleetScheduler(eng, max_batch=2, flush_ms=1.0, queue_limit=64,
                           per_tenant_inflight=4)
    assert sched.pipelined
    try:
        futs = [sched.submit(f"p{i}", None, i) for i in range(6)]
        for f in futs:
            f.result(timeout=30)
        assert len(eng.executed_pbs) >= 3
        saved = [pb.overlap_saved_ms for pb in eng.executed_pbs
                 if pb.overlap_saved_ms]
        assert saved and max(saved) > 1.0, (
            f"no prep/dispatch overlap measured: "
            f"{[pb.overlap_saved_ms for pb in eng.executed_pbs]}")
    finally:
        sched.shutdown()


def test_scheduler_pipelined_shutdown_drains_inflight(monkeypatch):
    """Satellite: shutdown with a batch mid-dispatch and another staged —
    both DRAIN (their futures resolve with results); queued-but-never-
    prepped futures fail cleanly with RuntimeError. Runs under the armed
    lock witness: the shutdown/drain handoff is exactly where the PR-11
    class of inversion would bite."""
    monkeypatch.setenv("ESCALATOR_TPU_LOCK_WITNESS", "1")
    witness_base = len(lockwitness.VIOLATIONS)
    eng = _FakeTwoStage(exec_sec=0.4)
    sched = FleetScheduler(eng, max_batch=1, flush_ms=1.0, queue_limit=64,
                           per_tenant_inflight=4)
    try:
        f1 = sched.submit("d1", None, 0)
        f2 = sched.submit("d2", None, 0)
        deadline = time.monotonic() + 5
        while not eng.batches and time.monotonic() < deadline:
            time.sleep(0.005)   # batch 1 inside the slow execute
        futs_late = [sched.submit(f"late{i}", None, 0) for i in range(3)]
    finally:
        sched.shutdown()
    assert f1.result(timeout=10)[0] == "decided"   # in-flight drained
    assert f2.result(timeout=10)[0] == "decided"   # staged drained
    failed = 0
    for f in futs_late:
        try:
            f.result(timeout=10)
        except RuntimeError:
            failed += 1
    assert failed == len(futs_late), "queued futures did not fail cleanly"
    assert lockwitness.VIOLATIONS[witness_base:] == [], \
        "pipelined shutdown tripped the lock-order witness"


def test_scheduler_stats_snapshot_fields():
    eng = _FakeEngine()
    sched = FleetScheduler(eng, max_batch=4, flush_ms=1.0)
    try:
        sched.submit("statty", None, 0).result(timeout=10)
        st = sched.stats()
        assert {"queue_depth", "admitted_total", "rejected_total",
                "deferred_total", "oldest_waiting_sec", "pipelined",
                "classes"} <= set(st)
        assert set(st["classes"]) == {"critical", "standard", "batch"}
        assert st["admitted_total"] == 1 and st["pipelined"] is False
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# codec framing
# ---------------------------------------------------------------------------


def test_codec_tenant_sidecar_round_trip():
    from escalator_tpu.plugin import codec

    c = tiny_cluster(1)
    frame = codec.encode_cluster(c, int(NOW), tenant={"id": "acme"})
    cluster, now, _ctx, tenant = codec.decode_cluster_full(frame)
    assert now == int(NOW) and tenant == {"id": "acme"}
    np.testing.assert_array_equal(cluster.pods.cpu_milli, c.pods.cpu_milli)
    # absence decodes as None (mixed-version peer)
    _c2, _n2, _ctx2, t2 = codec.decode_cluster_full(
        codec.encode_cluster(c, int(NOW)))
    assert t2 is None
    # old decoders (decode_cluster) ignore the sidecar entirely
    decoded, now2 = codec.decode_cluster(frame)
    assert now2 == int(NOW)
    np.testing.assert_array_equal(decoded.nodes.valid, c.nodes.valid)


def test_codec_torn_tenant_sidecar_is_present_but_invalid():
    import numpy as _np

    from escalator_tpu.plugin import codec

    c = tiny_cluster(2)
    named = [("__now__", _np.array([int(NOW)], _np.int64)),
             (codec._TENANT_KEY, _np.frombuffer(b"\xc1\xc1\xc1", _np.uint8))]
    for prefix, section in (("g.", c.groups), ("p.", c.pods),
                            ("n.", c.nodes)):
        for f in section.__dataclass_fields__:
            named.append((prefix + f, getattr(section, f)))
    _cl, _now, _ctx, tenant = codec.decode_cluster_full(
        codec._encode_arrays(named))
    # present-but-torn: the server must see "a tenant was intended" and
    # reject with INVALID_ARGUMENT, never silently fall back
    assert tenant == {"id": None}


def test_codec_fleet_response_sidecar_round_trip():
    import jax

    from escalator_tpu.plugin import codec

    c = tiny_cluster(3)
    out = kernel.decide_jit(jax.device_put(c), NOW)
    frame = codec.encode_decision(out, fleet={"ordered": False,
                                              "batch_size": 7})
    dec, _phases, fleet = codec.decode_decision_full(frame)
    assert fleet == {"ordered": False, "batch_size": 7}
    np.testing.assert_array_equal(np.asarray(dec.status),
                                  np.asarray(out.status))
    # absent from single-cluster peers
    _d2, _p2, f2 = codec.decode_decision_full(codec.encode_decision(out))
    assert f2 is None


def test_client_parses_retry_after_trailer():
    from escalator_tpu.plugin.client import _rpc_retry_after_sec

    class _Err:
        def trailing_metadata(self):
            return (("escalator-retry-after-ms", "250"),)

    class _NoMd:
        pass

    class _Torn:
        def trailing_metadata(self):
            return (("escalator-retry-after-ms", "not-a-number"),)

    assert _rpc_retry_after_sec(_Err()) == pytest.approx(0.25)
    assert _rpc_retry_after_sec(_NoMd()) is None
    assert _rpc_retry_after_sec(_Torn()) is None


# ---------------------------------------------------------------------------
# gRPC fleet mode end-to-end
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_plugin():
    from escalator_tpu.plugin.client import ComputeClient
    from escalator_tpu.plugin.server import FleetConfig, make_server

    server = make_server("127.0.0.1:0", max_workers=8, fleet=FleetConfig(
        num_groups=G, pod_capacity=P, node_capacity=N, max_tenants=8,
        max_batch=8, flush_ms=10.0, queue_limit=4, per_tenant_inflight=1,
        num_shards=2))
    server.start()
    client = ComputeClient(f"127.0.0.1:{server._escalator_bound_port}",
                           timeout_sec=180.0)
    # warm the fleet-step jit so per-test RPCs stay fast
    client.decide_arrays_fleet(tiny_cluster(0), int(NOW), "warm")
    yield server, client
    client.close()
    server.stop(grace=None)


def test_grpc_fleet_concurrent_tenants_coalesce_with_parity(fleet_plugin):
    _server, client = fleet_plugin
    clusters = {f"g{i}": tiny_cluster(60 + i) for i in range(4)}
    results = {}
    lock = threading.Lock()

    def one(tid, c):
        out, _phases, meta = client.decide_arrays_fleet(c, int(NOW), tid)
        with lock:
            results[tid] = (out, meta)

    threads = [threading.Thread(target=one, args=item)
               for item in clusters.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    batch_sizes = set()
    for tid, c in clusters.items():
        out, meta = results[tid]
        assert_column_parity(out, c, NOW, msg=tid)
        assert meta["tenant"] == tid
        assert meta.get("shard") in (0, 1)   # round 16: 2-shard fixture
        batch_sizes.add(meta["batch_size"])
    # coalescing observed: at least one multi-tenant micro-batch
    assert max(batch_sizes) >= 2, batch_sizes


def test_grpc_fleet_mixed_version_byte_identity(fleet_plugin):
    """Both mixed-version directions: an untagged frame on a fleet server
    and a tenant-tagged frame on a fleet-less server each produce the
    byte-identical single-cluster response (span recording off — the span
    sidecar carries per-call timings by design)."""
    from escalator_tpu import observability as obs
    from escalator_tpu.plugin import codec
    from escalator_tpu.plugin.client import ComputeClient
    from escalator_tpu.plugin.server import make_server

    _server, client = fleet_plugin
    plain = make_server("127.0.0.1:0")
    plain.start()
    plain_client = ComputeClient(
        f"127.0.0.1:{plain._escalator_bound_port}", timeout_sec=180.0)
    try:
        c = tiny_cluster(42)
        untagged = codec.encode_cluster(c, int(NOW))
        tagged = codec.encode_cluster(c, int(NOW), tenant={"id": "mixed"})
        obs.set_enabled(False)
        try:
            r_plain = plain_client._decide(untagged, timeout=120)
            assert client._decide(untagged, timeout=120) == r_plain
            assert plain_client._decide(tagged, timeout=120) == r_plain
        finally:
            obs.set_enabled(True)
    finally:
        plain_client.close()
        plain.stop(grace=None)


def test_grpc_fleet_malformed_tenant_is_invalid_argument(fleet_plugin):
    import grpc

    from escalator_tpu.plugin import codec

    _server, client = fleet_plugin
    for bad in ("", "x" * 300, 7):
        frame = codec.encode_cluster(tiny_cluster(1), int(NOW),
                                     tenant={"id": bad})
        with pytest.raises(grpc.RpcError) as ei:
            client._decide(frame, timeout=60)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    with pytest.raises(grpc.RpcError) as ei:
        client.evict_tenant("never-was-here")
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    # the batch was not poisoned: the next decide serves with full parity
    c = tiny_cluster(2)
    out, _p, meta = client.decide_arrays_fleet(c, int(NOW) + 60, "after-bad")
    assert_column_parity(out, c, int(NOW) + 60, msg="after-bad")
    assert meta["tenant"] == "after-bad"


def test_grpc_fleet_backpressure_resource_exhausted_with_retry_after(
        fleet_plugin):
    import grpc

    server, client = fleet_plugin
    sched = server._escalator_service.fleet
    sched.pause()
    rejected0 = sched.rejected_total
    outcomes = []
    lock = threading.Lock()

    def flood(i):
        try:
            client.decide_arrays_fleet(tiny_cluster(80 + i), int(NOW),
                                       f"flood{i}", max_attempts=1)
            with lock:
                outcomes.append("ok")
        except grpc.RpcError as e:
            md = dict(e.trailing_metadata() or ())
            with lock:
                outcomes.append(
                    (e.code().name, md.get("escalator-retry-after-ms")))

    threads = [threading.Thread(target=flood, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10
    while (sched.queue_depth + (sched.rejected_total - rejected0) < 6
           and time.monotonic() < deadline):
        time.sleep(0.02)   # all six queued/rejected against the paused worker
    sched.resume()
    for t in threads:
        t.join()
    rejected = [o for o in outcomes if o != "ok"]
    assert outcomes.count("ok") == 4 and len(rejected) == 2, outcomes
    for code, retry_after in rejected:
        assert code == "RESOURCE_EXHAUSTED"
        assert retry_after is not None and float(retry_after) > 0


def test_grpc_fleet_health_fields_and_evict(fleet_plugin):
    _server, client = fleet_plugin
    h = client.health()
    fleet = h["fleet"]
    assert fleet["tenants"] >= 1
    assert {"queue_depth", "admitted_total", "rejected_total",
            "oldest_waiting_sec", "batches", "buckets",
            # round 16: locked-snapshot counters + shard/pipeline/class SLO
            "deferred_total", "shards", "pipelined", "classes"} <= set(fleet)
    assert fleet["admitted_total"] > fleet["queue_depth"]
    assert fleet["shards"] == 2 and fleet["pipelined"] is True
    assert set(fleet["classes"]) == {"critical", "standard", "batch"}
    assert fleet["classes"]["critical"]["weight"] == 4
    ack = client.evict_tenant("warm")
    assert ack == {"evicted": "warm"}
    h2 = client.health()
    assert h2["fleet"]["tenants"] == fleet["tenants"] - 1


def test_grpc_backend_fleet_tenant_mode(fleet_plugin):
    """GrpcBackend(tenant_id=…): a full controller-backend decide rides the
    fleet path and honors the lazy-orders flag from the response sidecar."""
    from escalator_tpu.core import semantics as sem
    from escalator_tpu.plugin.client import GrpcBackend
    from escalator_tpu.testsupport.builders import (
        NodeOpts,
        PodOpts,
        build_test_nodes,
        build_test_pods,
    )

    server, _client = fleet_plugin
    backend = GrpcBackend(
        f"127.0.0.1:{server._escalator_bound_port}", timeout_sec=180.0,
        tenant_id="controller-a")
    pods = build_test_pods(4, PodOpts(cpu=[500], mem=[10**8]))
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    cfg = sem.GroupConfig(
        min_nodes=0, max_nodes=100, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=70, slow_removal_rate=1,
        fast_removal_rate=2)
    out = backend.decide([(pods, nodes, cfg, sem.GroupState())], int(NOW))
    assert out[0].decision.status == sem.DecisionStatus.OK
    assert out[0].decision.nodes_delta == 1   # 2000/2000=100% -> ceil(2*30/70)
    assert server._escalator_service.fleet.engine.has_tenant("controller-a")


def test_grpc_fleet_stream_session_delta_and_cache(fleet_plugin):
    """Round-18 streaming ingestion end to end through the real server:
    the FleetStreamSession's first decide ships a full frame, churned
    decides ship delta frames, and both stay bit-identical to a standalone
    decide on the session store's content. A repeated decide answers from
    the digest cache (``cached`` fleet sidecar + ``cached`` journey stage,
    batch_size 0); a set_groups reload and an evict both force misses."""
    import jax

    server, client = fleet_plugin
    from escalator_tpu.plugin.client import FleetStreamSession

    engine = server._escalator_service.fleet.engine

    def reference(sess, groups, now):
        from escalator_tpu.core.arrays import ClusterArrays

        pods, nodes = sess.store.as_pod_node_arrays()
        c = ClusterArrays(groups=_copy_soa(groups), pods=_copy_soa(pods),
                          nodes=_copy_soa(nodes))
        return kernel.decide_jit(jax.device_put(c), np.int64(now))

    groups = _copy_soa(tiny_cluster(80).groups)
    sess = FleetStreamSession(client, "stream-t", pod_capacity=P,
                              node_capacity=N, store_kind="numpy")
    sess.set_groups(groups)
    for i in range(6):
        sess.store.upsert_pod(f"p{i}", i % G, 500 + 10 * i, 10 ** 9, i % 4)
    for i in range(4):
        sess.store.upsert_node(f"n{i}", i % G, 4000, 16 * 10 ** 9)
    now = int(NOW)
    dec, _phases, fleet = sess.decide(now)
    assert sess.full_frames == 1 and sess.delta_frames == 0
    assert not fleet["cached"]
    ref = reference(sess, groups, now)
    for f in kernel.GROUP_DECISION_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(dec, f)), np.asarray(getattr(ref, f)),
            err_msg=f"full-frame {f}")
    # churn -> delta frame, still bit-exact
    sess.store.upsert_pod("p1", 1, 2000, 2 * 10 ** 9, 2)
    sess.store.delete_pod("p4")
    sess.store.upsert_node("n4", 4, 8000, 32 * 10 ** 9)
    dec, _phases, fleet = sess.decide(now + 60)
    assert sess.delta_frames == 1 and not fleet["cached"]
    ref = reference(sess, groups, now + 60)
    for f in kernel.GROUP_DECISION_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(dec, f)), np.asarray(getattr(ref, f)),
            err_msg=f"delta-frame {f}")
    # unchanged repeat -> digest cache answers (empty delta, no dispatch)
    hits = engine.cache_hits
    dec2, _phases, fleet = sess.decide(now + 60)
    assert fleet["cached"] and fleet["batch_size"] == 0
    assert engine.cache_hits == hits + 1
    assert "cached" in fleet["journey"]["stages_ms"]
    for f in kernel.GROUP_DECISION_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(dec2, f)), np.asarray(getattr(dec, f)),
            err_msg=f"cached {f}")
    # group reload: identical values still miss (semantic barrier)
    sess.set_groups(_copy_soa(groups))
    _dec, _phases, fleet = sess.decide(now + 60)
    assert not fleet["cached"] and engine.cache_hits == hits + 1
    # evict -> the session resyncs with a full frame and starts cold
    sess.evict()
    full_before = sess.full_frames
    _dec, _phases, fleet = sess.decide(now + 60)
    assert sess.full_frames == full_before + 1 and not fleet["cached"]
