"""Parity: the batched JAX kernel must reproduce the golden semantics element-wise on
randomized clusters — the contract demanded by SURVEY.md §4/§7 (kernel vs reference Go
math, here kernel vs the ported golden model)."""

import random

import numpy as np
import pytest

from escalator_tpu.core import semantics as sem
from escalator_tpu.core.arrays import pack_cluster
from escalator_tpu.k8s import types as k8s
from escalator_tpu.ops import kernel
from escalator_tpu.testsupport.builders import NodeOpts, PodOpts, build_test_node, build_test_pod

NOW = 1_700_000_000


def random_group(rng: random.Random, gi: int):
    """A randomized nodegroup snapshot covering all decision branches."""
    scenario = rng.choice(
        ["normal", "empty", "all_tainted", "zero_cap", "below_min", "above_max", "locked"]
    )
    config = sem.GroupConfig(
        min_nodes=rng.randint(0, 3),
        max_nodes=rng.randint(5, 40),
        taint_lower_percent=30,
        taint_upper_percent=45,
        scale_up_percent=70,
        slow_removal_rate=rng.randint(1, 2),
        fast_removal_rate=rng.randint(2, 5),
        soft_delete_grace_sec=300,
        hard_delete_grace_sec=900,
    )
    state = sem.GroupState(
        locked=(scenario == "locked"),
        requested_nodes=rng.randint(0, 7),
        cached_cpu_milli=rng.choice([0, 1000, 4000]),
        cached_mem_bytes=rng.choice([0, 10**9]),
    )

    nodes = []
    pods = []
    if scenario != "empty":
        n_nodes = {
            "below_min": max(0, config.min_nodes - 1),
            "above_max": config.max_nodes + rng.randint(1, 3),
        }.get(scenario, rng.randint(max(1, config.min_nodes), config.max_nodes))
        for i in range(n_nodes):
            tainted = scenario == "all_tainted" or rng.random() < 0.2
            cordoned = (not tainted) and rng.random() < 0.1
            cap_cpu = 0 if scenario == "zero_cap" else rng.choice([1000, 2000, 4000])
            cap_mem = 0 if scenario == "zero_cap" else rng.choice([10**9, 4 * 10**9])
            nodes.append(
                build_test_node(
                    NodeOpts(
                        name=f"g{gi}-n{i}",
                        cpu=cap_cpu,
                        mem=cap_mem,
                        creation_time_ns=rng.randint(1, 10**9) * 1000,
                        tainted=tainted,
                        taint_time_sec=NOW - rng.randint(0, 2000) if tainted else None,
                        cordoned=cordoned,
                        no_delete=rng.random() < 0.1,
                    )
                )
            )
        n_pods = rng.randint(0, 30)
        for i in range(n_pods):
            target = rng.choice(nodes).name if nodes and rng.random() < 0.7 else ""
            pods.append(
                build_test_pod(
                    PodOpts(
                        name=f"g{gi}-p{i}",
                        cpu=[rng.choice([100, 250, 500, 1000])],
                        mem=[rng.choice([10**8, 5 * 10**8, 10**9])],
                        node_name=target,
                    )
                )
            )
    return pods, nodes, config, state


def eval_group_golden(pods, nodes, config, state):
    """Golden decision + selections + reap for one group."""
    decision = sem.evaluate_node_group(pods, nodes, config, dataclass_copy(state))
    untainted, tainted, _ = sem.filter_nodes(nodes)
    down_order = [untainted[i].name for i in sem.nodes_oldest_first(untainted)]
    up_order = [tainted[i].name for i in sem.nodes_newest_first(tainted)]
    info = k8s.create_node_name_to_info_map(pods, nodes)
    reap = {
        tainted[i].name
        for i in sem.reap_eligible(
            tainted, info, config.soft_delete_grace_sec, config.hard_delete_grace_sec, NOW
        )
    }
    return decision, down_order, up_order, reap


def dataclass_copy(state):
    return sem.GroupState(**state.__dict__)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_kernel_matches_golden(seed):
    rng = random.Random(seed)
    G = 24
    groups = [random_group(rng, gi) for gi in range(G)]

    cluster = pack_cluster(groups, pad_pods=1024, pad_nodes=512, pad_groups=32)
    out = kernel.decide_jit(cluster, np.int64(NOW))
    status = np.asarray(out.status)
    delta = np.asarray(out.nodes_delta)
    cpu_pct = np.asarray(out.cpu_percent)
    mem_pct = np.asarray(out.mem_percent)
    down_order = np.asarray(out.scale_down_order)
    up_order = np.asarray(out.untaint_order)
    u_off = np.asarray(out.untainted_offsets)
    t_off = np.asarray(out.tainted_offsets)
    reap_mask = np.asarray(out.reap_mask)

    # node index -> name for selection comparison
    node_names = []
    for _, nodes, _, _ in groups:
        node_names.extend(n.name for n in nodes)

    for gi, (pods, nodes, config, state) in enumerate(groups):
        want, want_down, want_up, want_reap = eval_group_golden(
            pods, nodes, config, state
        )
        assert status[gi] == int(want.status), (
            f"group {gi}: status {status[gi]} != {want.status}"
        )
        assert delta[gi] == want.nodes_delta, (
            f"group {gi} ({want.status.name}): delta {delta[gi]} != {want.nodes_delta}"
        )
        # every aggregate field, against the golden Decision — including the
        # zero sums on pre-aggregation exits (the 10x-soak regression class)
        for field in ("cpu_request_milli", "mem_request_bytes",
                      "cpu_capacity_milli", "mem_capacity_bytes", "num_pods",
                      "num_nodes", "num_untainted", "num_tainted",
                      "num_cordoned"):
            assert int(getattr(out, field)[gi]) == int(getattr(want, field)), (
                f"group {gi} ({want.status.name}): {field}"
            )
        if want.status not in (
            sem.DecisionStatus.NOOP_EMPTY,
            sem.DecisionStatus.ERR_BELOW_MIN,
            sem.DecisionStatus.ERR_ABOVE_MAX,
            sem.DecisionStatus.FORCED_MIN_SCALE_UP,
            sem.DecisionStatus.ERR_DIV_ZERO,
        ):
            assert cpu_pct[gi] == want.cpu_percent
            assert mem_pct[gi] == want.mem_percent

        got_down = [node_names[i] for i in down_order[u_off[gi] : u_off[gi + 1]]]
        got_up = [node_names[i] for i in up_order[t_off[gi] : t_off[gi + 1]]]
        assert got_down == want_down, f"group {gi} scale-down order"
        assert got_up == want_up, f"group {gi} untaint order"

        got_reap = {
            node_names[i]
            for i in np.nonzero(reap_mask)[0]
            if i < len(node_names) and node_names[i].startswith(f"g{gi}-")
        }
        assert got_reap == want_reap, f"group {gi} reap set"


def test_aggregates_match():
    rng = random.Random(42)
    groups = [random_group(rng, gi) for gi in range(8)]
    cluster = pack_cluster(groups)
    out = kernel.decide_jit(cluster, np.int64(NOW))
    for gi, (pods, nodes, config, state) in enumerate(groups):
        # the golden model IS the expectation — including its zero sums on
        # the pre-aggregation exits (don't re-derive its conditions here)
        want = sem.evaluate_node_group(pods, nodes, config,
                                       dataclass_copy(state))
        for field in ("cpu_request_milli", "mem_request_bytes",
                      "cpu_capacity_milli", "mem_capacity_bytes",
                      "num_pods", "num_nodes", "num_untainted",
                      "num_tainted", "num_cordoned"):
            assert int(getattr(out, field)[gi]) == getattr(want, field), (
                f"group {gi} ({want.status.name}): {field}"
            )


def test_above_max_group_reports_zero_sums_like_golden():
    """Regression for the 10x-soak find: a group past max_nodes must report
    ZERO request/capacity sums (counts stay) — exactly the golden Decision,
    whose ERR_ABOVE_MAX return precedes aggregation, reference
    controller.go:247-255."""
    cfg = sem.GroupConfig(min_nodes=0, max_nodes=2, taint_lower_percent=30,
                          taint_upper_percent=45, scale_up_percent=70,
                          slow_removal_rate=1, fast_removal_rate=2)
    nodes = [build_test_node(NodeOpts(name=f"n{i}", cpu=4000, mem=16 * 10**9))
             for i in range(4)]  # 4 > max 2
    pods = [build_test_pod(PodOpts(name=f"p{i}", cpu=[500], mem=[10**9]))
            for i in range(3)]
    want = sem.evaluate_node_group(pods, nodes, cfg, sem.GroupState())
    assert want.status == sem.DecisionStatus.ERR_ABOVE_MAX
    out = kernel.decide_jit(
        pack_cluster([(pods, nodes, cfg, sem.GroupState())]), np.int64(NOW))
    for field in ("cpu_request_milli", "mem_request_bytes",
                  "cpu_capacity_milli", "mem_capacity_bytes"):
        assert int(getattr(out, field)[0]) == getattr(want, field) == 0, field
    assert int(out.num_nodes[0]) == want.num_nodes == 4
    assert int(out.num_pods[0]) == want.num_pods == 3


def test_padding_lanes_inert():
    rng = random.Random(7)
    groups = [random_group(rng, gi) for gi in range(3)]
    cluster = pack_cluster(groups, pad_pods=256, pad_nodes=128, pad_groups=16)
    out = kernel.decide_jit(cluster, np.int64(NOW))
    for gi in range(3, 16):
        assert int(out.status[gi]) == int(sem.DecisionStatus.NOOP_EMPTY)
        assert int(out.nodes_delta[gi]) == 0


def test_zero_threshold_is_deterministic_error():
    """scale_up_percent <= 0 is invalid config (reference rejects it at startup,
    node_group.go:96); both golden and kernel must agree on ERR_NEG_DELTA, never
    NaN-derived garbage."""
    from escalator_tpu.testsupport.builders import build_test_nodes, build_test_pods

    cfg = sem.GroupConfig(min_nodes=0, max_nodes=10, taint_lower_percent=0,
                          taint_upper_percent=0, scale_up_percent=0,
                          slow_removal_rate=1, fast_removal_rate=2)
    pods = build_test_pods(1, PodOpts(cpu=[100], mem=[100]))
    nodes = build_test_nodes(1, NodeOpts(cpu=1000, mem=1000))
    want = sem.evaluate_node_group(pods, nodes, cfg, sem.GroupState())
    assert want.status == sem.DecisionStatus.ERR_NEG_DELTA
    cluster = pack_cluster([(pods, nodes, cfg, sem.GroupState())])
    out = kernel.decide_jit(cluster, np.int64(NOW))
    assert int(out.status[0]) == int(want.status)
    assert int(out.nodes_delta[0]) == want.nodes_delta == 0


def test_huge_delta_clamped_identically():
    """Deltas are clamped to int32 in both models (semantics.MAX_DELTA)."""
    from escalator_tpu.testsupport.builders import build_test_nodes, build_test_pods

    cfg = sem.GroupConfig(min_nodes=0, max_nodes=10, taint_lower_percent=30,
                          taint_upper_percent=45, scale_up_percent=1,
                          slow_removal_rate=1, fast_removal_rate=2)
    # scale-from-zero with tiny cached capacity and a colossal request
    nodes = build_test_nodes(1, NodeOpts(cpu=1, mem=1, tainted=True, taint_time_sec=1))
    pods = build_test_pods(1, PodOpts(cpu=[10**15], mem=[10**15]))
    st1, st2 = sem.GroupState(), sem.GroupState()
    want = sem.evaluate_node_group(pods, nodes, cfg, st1)
    cluster = pack_cluster([(pods, nodes, cfg, st2)])
    out = kernel.decide_jit(cluster, np.int64(NOW))
    assert want.nodes_delta == sem.MAX_DELTA
    assert int(out.nodes_delta[0]) == want.nodes_delta
    assert int(out.status[0]) == int(want.status)


def test_scale_up_delta_float_order_parity():
    """Op-order regression: Go computes n*((pct-thr)/thr); the grouping changes the
    result by one node on this input (543 nodes, 5430m cap, 1632m req, thr 15)."""
    from escalator_tpu.testsupport.builders import build_test_nodes, build_test_pods

    cfg = sem.GroupConfig(min_nodes=0, max_nodes=10**6, taint_lower_percent=1,
                          taint_upper_percent=2, scale_up_percent=15,
                          slow_removal_rate=1, fast_removal_rate=2)
    nodes = build_test_nodes(543, NodeOpts(cpu=10, mem=10**6))
    pods = build_test_pods(1, PodOpts(cpu=[1632], mem=[10**5]))
    want = sem.evaluate_node_group(pods, nodes, cfg, sem.GroupState())
    assert want.nodes_delta == 545  # ceil(543*((30.055...-15)/15))
    cluster = pack_cluster([(pods, nodes, cfg, sem.GroupState())])
    out = kernel.decide_jit(cluster, np.int64(NOW))
    assert int(out.nodes_delta[0]) == want.nodes_delta


def test_native_tick_impl_selection(monkeypatch):
    """The native tick defaults to the Pallas sweep on an accelerator (its
    slot-reused layout is the sorted path's measured win) and to XLA scatter
    on CPU. ESCALATOR_TPU_KERNEL_IMPL overrides — except that a stale
    ``pallas`` config on a platform without compiled Pallas (the CPU
    fallback) auto-selects xla with a one-time log (round 8: cfg9 measured
    interpreter Pallas losing 5.8-120x on every row); ``pallas-force`` is
    the explicit escape hatch that always means interpreter-or-compiled
    Pallas."""
    monkeypatch.delenv("ESCALATOR_TPU_KERNEL_IMPL", raising=False)
    assert kernel.native_tick_impl("tpu") == "pallas"
    assert kernel.native_tick_impl("axon") == "pallas"  # tunnel platform name
    assert kernel.native_tick_impl("cpu") == "xla"
    # compiled Pallas is TPU-only: a gpu platform must NOT be handed
    # interpreter-mode Pallas on the hot path
    assert kernel.native_tick_impl("gpu") == "xla"
    # the whitelist is shared with pallas_kernel._use_interpret — pin the
    # single source so the two selectors cannot drift
    from escalator_tpu.jaxconfig import PALLAS_COMPILED_PLATFORMS

    for p in PALLAS_COMPILED_PLATFORMS:
        assert kernel.native_tick_impl(p) == "pallas"
    # SET-but-empty env propagates (decide() fails fast on it), matching
    # default_impl's behavior for the repack backends
    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "")
    assert kernel.native_tick_impl("tpu") == ""
    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "xla")
    assert kernel.native_tick_impl("tpu") == "xla"
    # the round-8 CPU-fallback guard: a stale pallas config on a
    # non-Pallas-compiled platform degrades to xla instead of silently
    # running the interpreter on the hot path; on TPU it is honored
    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "pallas")
    assert kernel.native_tick_impl("cpu") == "xla"
    assert kernel.native_tick_impl("gpu") == "xla"
    assert kernel.native_tick_impl("tpu") == "pallas"
    assert kernel.default_impl(platform="cpu") == "xla"
    assert kernel.default_impl(platform="tpu") == "pallas"
    # the explicit escape hatch (tests/debug want interpreter Pallas)
    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "pallas-force")
    assert kernel.native_tick_impl("cpu") == "pallas"
    assert kernel.default_impl(platform="cpu") == "pallas"
    # misconfiguration still fails fast downstream: invalid values pass
    # through untouched for decide()'s ValueError
    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "palas")
    assert kernel.native_tick_impl("cpu") == "palas"


def test_impl_autoselect_logs_once(monkeypatch, caplog):
    """The CPU-fallback auto-select names its measured reason ONCE per
    platform per process, not per tick."""
    import logging

    monkeypatch.setattr(kernel, "_AUTOSELECT_LOGGED", set())
    with caplog.at_level(logging.WARNING, logger="escalator_tpu.kernel"):
        assert kernel._resolve_impl_env("pallas", "cpu") == "xla"
        assert kernel._resolve_impl_env("pallas", "cpu") == "xla"
    msgs = [r for r in caplog.records if "auto-selecting" in r.getMessage()]
    assert len(msgs) == 1
    assert "cfg9" in msgs[0].getMessage()  # the measured reason, named


def test_make_backend_probes_accelerator(monkeypatch):
    """Every jax-dispatching backend kind must run the wedged-transport probe
    (centralized in make_backend so new entry points are safe by
    construction); golden must not touch it."""
    from escalator_tpu import jaxconfig
    from escalator_tpu.controller import backend as bmod

    probed = []
    monkeypatch.setattr(jaxconfig, "ensure_responsive_accelerator",
                        lambda *a, **k: probed.append(True) or True)
    bmod.make_backend("golden")
    assert probed == []
    bmod.make_backend("jax")
    assert probed == [True]
    with pytest.raises(ValueError):
        bmod.make_backend("not-a-backend")
    assert probed == [True]  # unknown kinds fail fast before probing


NON_ORDER_FIELDS = (
    "status nodes_delta cpu_percent mem_percent cpu_request_milli "
    "mem_request_bytes cpu_capacity_milli mem_capacity_bytes num_pods "
    "num_nodes num_untainted num_tainted num_cordoned untainted_offsets "
    "tainted_offsets reap_mask node_pods_remaining"
).split()


@pytest.mark.parametrize("seed", [5, 6])
def test_light_decide_matches_full_on_non_order_fields(seed):
    """with_orders=False (the lazy-orders light program) must bit-match the
    full decide on every field EXCEPT the two order permutations — the
    contract kernel.lazy_orders_decide and the native backend's healthy-tick
    fast path rely on."""
    rng = random.Random(seed)
    groups = [random_group(rng, gi) for gi in range(16)]
    cluster = pack_cluster(groups, pad_pods=1024, pad_nodes=512)
    full = kernel.decide_jit(cluster, np.int64(NOW))
    light = kernel.decide_jit(cluster, np.int64(NOW), with_orders=False)
    for field in NON_ORDER_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(light, field)), np.asarray(getattr(full, field)),
            err_msg=f"light-vs-full mismatch on {field}",
        )


def test_lazy_orders_decide_protocol():
    """The gate: tainted state sorts up front; a negative delta re-dispatches
    with orders; a healthy steady-state tick never sorts."""
    calls = []

    def make_dispatch(cluster):
        def dispatch(with_orders):
            calls.append(with_orders)
            return kernel.decide_jit(cluster, np.int64(NOW),
                                     with_orders=with_orders)
        return dispatch

    # tainted present -> one ordered dispatch, no light attempt
    rng = random.Random(7)
    groups = [random_group(rng, gi) for gi in range(8)]
    cluster = pack_cluster(groups)
    tainted_exists = bool(
        (np.asarray(cluster.nodes.valid)
         & np.asarray(cluster.nodes.tainted)).any())
    assert tainted_exists, "seed must produce tainted nodes"
    out, ordered = kernel.lazy_orders_decide(make_dispatch(cluster), True)
    assert ordered and calls == [True]

    # healthy low-usage group -> delta < 0 -> light then ordered re-dispatch
    calls.clear()
    opts = PodOpts(cpu=[100], mem=[10**8])
    pods = [build_test_pod(opts)]
    nodes = [
        build_test_node(NodeOpts(name=f"h{i}", cpu=4000, mem=16 * 10**9))
        for i in range(6)
    ]
    cfg = sem.GroupConfig(
        min_nodes=1, max_nodes=30, taint_lower_percent=30,
        taint_upper_percent=45, scale_up_percent=70, slow_removal_rate=1,
        fast_removal_rate=2, soft_delete_grace_sec=300,
        hard_delete_grace_sec=900,
    )
    drain = pack_cluster([(pods, nodes, cfg, sem.GroupState())])
    out, ordered = kernel.lazy_orders_decide(make_dispatch(drain), False)
    assert ordered and calls == [False, True]
    assert int(np.asarray(out.nodes_delta)[0]) < 0
    # the re-dispatched result carries REAL orders: the untainted window is
    # the golden oldest-first victim order
    u_off = np.asarray(out.untainted_offsets)
    down = np.asarray(out.scale_down_order)[u_off[0]:u_off[1]]
    golden = sem.nodes_oldest_first(nodes)
    assert [nodes[i].name for i in golden] == [
        nodes[i].name for i in down
    ]

    # steady-state (delta 0, no tainted) -> one light dispatch, no sort
    calls.clear()
    from escalator_tpu.testsupport.builders import build_test_pods

    # 12 pods x 500m = 6000m on 3 nodes x 4000m = 50% — inside the
    # (taint_upper 45, scale_up 70) no-action band
    balanced_pods = build_test_pods(12, PodOpts(cpu=[500], mem=[10**9]))
    balanced = pack_cluster(
        [(balanced_pods, nodes[:3], cfg, sem.GroupState())])
    out, ordered = kernel.lazy_orders_decide(make_dispatch(balanced), False)
    assert not ordered and calls == [False]
    assert int(np.asarray(out.nodes_delta)[0]) == 0
